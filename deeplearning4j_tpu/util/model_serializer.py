"""ModelSerializer — the model zip container.

Reference: dl4j-nn ``org.deeplearning4j.util.ModelSerializer`` (SURVEY.md
§5.4): zip = configuration.json + coefficients.bin (flattened params) +
updaterState.bin + optional normalizer.bin. Same inventory here with npz
payloads; the JSON topology comes from MultiLayerConfiguration.to_json so a
config round-trips independently of weights.
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Optional

import jax
import numpy as np

_CONF_ENTRY = "configuration.json"
_COEFF_ENTRY = "coefficients.npz"
_UPDATER_ENTRY = "updaterState.npz"
_NORMALIZER_ENTRY = "normalizer.json"
_META_ENTRY = "meta.json"


def write_model(model, path: str, save_updater: bool = False,
                normalizer=None) -> None:
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr(_CONF_ENTRY, model.conf.to_json())
        leaves, _ = jax.tree.flatten(model._params)
        buf = io.BytesIO()
        np.savez(buf, **{str(i): np.asarray(l) for i, l in enumerate(leaves)})
        zf.writestr(_COEFF_ENTRY, buf.getvalue())
        # batchnorm running stats etc.
        sleaves, _ = jax.tree.flatten(model._states)
        sbuf = io.BytesIO()
        np.savez(sbuf, **{str(i): np.asarray(l) for i, l in enumerate(sleaves)})
        zf.writestr("states.npz", sbuf.getvalue())
        zf.writestr(_META_ENTRY, json.dumps({
            "iteration": model._iteration, "epoch": model._epoch,
            "format_version": 1,
        }))
        if save_updater and model._updater_state is not None:
            uleaves, _ = jax.tree.flatten(model._updater_state)
            ubuf = io.BytesIO()
            np.savez(ubuf, **{str(i): np.asarray(l) for i, l in enumerate(uleaves)})
            zf.writestr(_UPDATER_ENTRY, ubuf.getvalue())
        if normalizer is not None:
            zf.writestr(_NORMALIZER_ENTRY, json.dumps(normalizer.to_json()))


def restore_multi_layer_network(path: str, load_updater: bool = False):
    from ..nn.conf.builder import MultiLayerConfiguration
    from ..nn.multilayer import MultiLayerNetwork

    with zipfile.ZipFile(path) as zf:
        conf = MultiLayerConfiguration.from_json(zf.read(_CONF_ENTRY).decode())
        model = MultiLayerNetwork(conf)
        model.init()
        coeffs = np.load(io.BytesIO(zf.read(_COEFF_ENTRY)))
        leaves, treedef = jax.tree.flatten(model._params)
        if len(coeffs.files) != len(leaves):
            raise ValueError(
                f"coefficient count mismatch: archive has {len(coeffs.files)}, "
                f"configuration implies {len(leaves)}")
        restored = [np.asarray(coeffs[str(i)]) for i in range(len(leaves))]
        model._params = jax.tree.unflatten(
            treedef, [l.astype(np.asarray(o).dtype) for l, o in zip(restored, leaves)])
        if "states.npz" in zf.namelist():
            states = np.load(io.BytesIO(zf.read("states.npz")))
            sleaves, streedef = jax.tree.flatten(model._states)
            model._states = jax.tree.unflatten(
                streedef, [np.asarray(states[str(i)]) for i in range(len(sleaves))])
        meta = json.loads(zf.read(_META_ENTRY))
        model._iteration = meta.get("iteration", 0)
        model._epoch = meta.get("epoch", 0)
        if load_updater and _UPDATER_ENTRY in zf.namelist():
            upd = model.conf.global_conf.updater
            state0 = upd.init(model._params)
            uleaves, utreedef = jax.tree.flatten(state0)
            data = np.load(io.BytesIO(zf.read(_UPDATER_ENTRY)))
            model._updater_state = jax.tree.unflatten(
                utreedef, [np.asarray(data[str(i)]) for i in range(len(uleaves))])
    return model


def restore_normalizer(path: str):
    from ..data.normalizers import normalizer_from_json

    with zipfile.ZipFile(path) as zf:
        if _NORMALIZER_ENTRY not in zf.namelist():
            return None
        return normalizer_from_json(json.loads(zf.read(_NORMALIZER_ENTRY)))
