"""ModelSerializer — the model zip container.

Reference: dl4j-nn ``org.deeplearning4j.util.ModelSerializer`` (SURVEY.md
§5.4): zip = configuration.json + coefficients.bin (flattened params) +
updaterState.bin + optional normalizer.bin. Same inventory here with npz
payloads; one shared writer/restorer serves both MultiLayerNetwork and
ComputationGraph (``writeModel/restoreMultiLayerNetwork/
restoreComputationGraph`` contract).
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Optional

import jax
import numpy as np

_CONF_ENTRY = "configuration.json"
_COEFF_ENTRY = "coefficients.npz"
_STATES_ENTRY = "states.npz"
_UPDATER_ENTRY = "updaterState.npz"
_NORMALIZER_ENTRY = "normalizer.json"
_META_ENTRY = "meta.json"


def _savez_leaves(tree) -> bytes:
    leaves, _ = jax.tree.flatten(tree)
    buf = io.BytesIO()
    np.savez(buf, **{str(i): np.asarray(l) for i, l in enumerate(leaves)})
    return buf.getvalue()


def _load_into_tree(data: bytes, template, what: str, cast_to_template: bool = False):
    arrays = np.load(io.BytesIO(data))
    leaves, treedef = jax.tree.flatten(template)
    if len(arrays.files) != len(leaves):
        raise ValueError(
            f"{what} count mismatch: archive has {len(arrays.files)}, "
            f"configuration implies {len(leaves)}")
    restored = [np.asarray(arrays[str(i)]) for i in range(len(leaves))]
    if cast_to_template:
        restored = [r.astype(np.asarray(t).dtype) for r, t in zip(restored, leaves)]
    return jax.tree.unflatten(treedef, restored)


def write_model(model, path: str, save_updater: bool = False,
                normalizer=None) -> None:
    """Shared writer for MultiLayerNetwork and ComputationGraph. The zip
    is staged to ``<path>.tmp`` and renamed into place, so a crash
    mid-save never leaves a torn file at the target name (the same
    atomicity contract util.checkpoint builds its manifest on)."""
    import os

    tmp = path + ".tmp"
    try:
        with zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr(_CONF_ENTRY, model.conf.to_json())
            zf.writestr(_COEFF_ENTRY, _savez_leaves(model._params))
            zf.writestr(_STATES_ENTRY, _savez_leaves(model._states))
            zf.writestr(_META_ENTRY, json.dumps({
                "iteration": model._iteration, "epoch": model._epoch,
                "kind": type(model).__name__, "format_version": 1,
            }))
            if save_updater and model._updater_state is not None:
                # a ZeRO-1 fit leaves the updater state in the flat
                # sharded layout; the container's layout is ALWAYS the
                # dense params-mirroring tree (see util.checkpoint)
                from ..parallel.sharding import unflatten_updater_state

                upd = unflatten_updater_state(
                    jax.device_get(model._updater_state),
                    jax.device_get(model._params))
                zf.writestr(_UPDATER_ENTRY, _savez_leaves(upd))
            if normalizer is not None:
                zf.writestr(_NORMALIZER_ENTRY,
                            json.dumps(normalizer.to_json()))
        os.replace(tmp, path)
    except BaseException:
        # don't strand a half-written tmp at an arbitrary user path
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def _materialize_on_device(tree):
    """Restored trees become DEVICE arrays before they reach a model: the
    fit step donates these buffers, and donating an array that zero-copy
    aliases numpy-owned host memory (possible on the CPU backend) frees
    memory numpy still owns — observed as glibc heap corruption under the
    persistent compilation cache."""
    import jax.numpy as jnp

    return jax.tree.map(lambda a: jnp.array(jnp.asarray(a)), tree)


def load_state_entries(zf: zipfile.ZipFile, model,
                       load_updater: bool = True) -> None:
    """Load the container's coefficient/state/meta(/updater) entries INTO
    an existing initialized model, device-materialized. Shared by
    :func:`_restore` (fresh model from the zip's conf) and
    ``util.checkpoint.restore_training_state`` (resume into a live model)
    so the donation-safety materialization cannot drift between them."""
    names = zf.namelist()
    model._params = _materialize_on_device(_load_into_tree(
        zf.read(_COEFF_ENTRY), model._params, "coefficient",
        cast_to_template=True))
    if _STATES_ENTRY in names:
        model._states = _materialize_on_device(_load_into_tree(
            zf.read(_STATES_ENTRY), model._states, "state"))
    meta = json.loads(zf.read(_META_ENTRY))
    model._iteration = meta.get("iteration", 0)
    model._epoch = meta.get("epoch", 0)
    if load_updater:
        if _UPDATER_ENTRY in names:
            state0 = model.conf.global_conf.updater.init(model._params)
            model._updater_state = _materialize_on_device(_load_into_tree(
                zf.read(_UPDATER_ENTRY), state0, "updater state"))
        else:
            model._updater_state = None


def _restore(path: str, model_cls, conf_cls, load_updater: bool):
    with zipfile.ZipFile(path) as zf:
        conf = conf_cls.from_json(zf.read(_CONF_ENTRY).decode())
        model = model_cls(conf)
        model.init()
        load_state_entries(zf, model, load_updater=load_updater)
    return model


def restore_multi_layer_network(path: str, load_updater: bool = False):
    from ..nn.conf.builder import MultiLayerConfiguration
    from ..nn.multilayer import MultiLayerNetwork

    return _restore(path, MultiLayerNetwork, MultiLayerConfiguration, load_updater)


def restore_computation_graph(path: str, load_updater: bool = False):
    from ..nn.graph import ComputationGraph, ComputationGraphConfiguration

    return _restore(path, ComputationGraph, ComputationGraphConfiguration, load_updater)


def restore_model(path: str, load_updater: bool = False):
    """Restore either model class, dispatching on the container's
    ``meta.json`` kind entry (reference ``ModelSerializer.restore*`` pair,
    merged — the zip records what it holds)."""
    with zipfile.ZipFile(path) as zf:
        meta = json.loads(zf.read(_META_ENTRY))
    if meta.get("kind") == "ComputationGraph":
        return restore_computation_graph(path, load_updater)
    return restore_multi_layer_network(path, load_updater)


def restore_normalizer(path: str):
    from ..data.normalizers import normalizer_from_json

    with zipfile.ZipFile(path) as zf:
        if _NORMALIZER_ENTRY not in zf.namelist():
            return None
        return normalizer_from_json(json.loads(zf.read(_NORMALIZER_ENTRY)))
