"""Vocabulary construction + Huffman coding for hierarchical softmax.

Rebuild of the reference's vocab layer (reference layout: deeplearning4j-nlp
``models/word2vec/wordstore`` — ``VocabWord``, ``AbstractCache``,
``VocabConstructor`` — and ``models/word2vec/Huffman``). Behavior parity:

- frequency count over the tokenized corpus, prune below ``min_word_frequency``
- words sorted by descending frequency, index 0 = most frequent
- Huffman tree over word frequencies assigns each word a binary ``code`` and
  the list of inner-node indices (``points``) on its root path — consumed by
  the hierarchical-softmax training path
- unigram table with the canonical f^0.75 smoothing for negative sampling

All host-side; the outputs are dense numpy arrays (codes/points padded +
masked) shaped for the vectorized device step rather than the reference's
per-word Java lists.
"""

from __future__ import annotations

import heapq
from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np


@dataclass
class VocabWord:
    """One vocabulary entry (reference: VocabWord)."""

    word: str
    count: int
    index: int = -1
    # Hierarchical-softmax Huffman path: bits + inner-node ids, root-first.
    code: List[int] = field(default_factory=list)
    points: List[int] = field(default_factory=list)


class VocabCache:
    """Word ↔ index ↔ frequency store (reference: AbstractCache)."""

    def __init__(self) -> None:
        self._words: List[VocabWord] = []
        self._by_word: Dict[str, VocabWord] = {}
        self.total_word_count = 0

    def add(self, vw: VocabWord) -> None:
        vw.index = len(self._words)
        self._words.append(vw)
        self._by_word[vw.word] = vw
        self.total_word_count += vw.count

    def __len__(self) -> int:
        return len(self._words)

    def __contains__(self, word: str) -> bool:
        return word in self._by_word

    def word_for(self, index: int) -> str:
        return self._words[index].word

    def index_of(self, word: str) -> int:
        vw = self._by_word.get(word)
        return -1 if vw is None else vw.index

    def entry(self, word: str) -> Optional[VocabWord]:
        return self._by_word.get(word)

    def entry_at(self, index: int) -> VocabWord:
        return self._words[index]

    def words(self) -> List[str]:
        return [w.word for w in self._words]

    def counts(self) -> np.ndarray:
        return np.asarray([w.count for w in self._words], dtype=np.int64)


class VocabConstructor:
    """Scan corpus → pruned, frequency-sorted VocabCache (reference:
    VocabConstructor.buildJointVocabulary)."""

    def __init__(self, min_word_frequency: int = 5,
                 special_tokens: Sequence[str] = ()):
        self.min_word_frequency = min_word_frequency
        self.special_tokens = list(special_tokens)

    def build(self, token_stream: Iterable[List[str]]) -> VocabCache:
        counts: Counter = Counter()
        for tokens in token_stream:
            counts.update(tokens)
        cache = VocabCache()
        # Special tokens (e.g. ParagraphVectors doc labels) are exempt from
        # frequency pruning, matching the reference's markAsSpecial handling.
        for tok in self.special_tokens:
            cache.add(VocabWord(tok, max(counts.pop(tok, 0), 1)))
        kept = [(w, c) for w, c in counts.items()
                if c >= self.min_word_frequency]
        # Descending frequency, ties by word for determinism.
        kept.sort(key=lambda wc: (-wc[1], wc[0]))
        for w, c in kept:
            cache.add(VocabWord(w, c))
        return cache


def build_huffman(cache: VocabCache, max_code_length: int = 40) -> None:
    """Assign Huffman ``code``/``points`` to every VocabWord in-place
    (reference: models/word2vec/Huffman.java — same tree construction:
    repeatedly merge the two least-frequent nodes; inner node ids are
    ``node_id - vocab_size`` so they index the syn1 matrix).
    """
    n = len(cache)
    if n == 0:
        return
    # heap entries: (count, tiebreak, node_id). Leaves are 0..n-1; inner
    # nodes take ids n..2n-2.
    heap = [(cache.entry_at(i).count, i, i) for i in range(n)]
    heapq.heapify(heap)
    parent = np.zeros(2 * n, dtype=np.int64)
    binary = np.zeros(2 * n, dtype=np.int8)
    next_id = n
    while len(heap) > 1:
        c1, _, i1 = heapq.heappop(heap)
        c2, _, i2 = heapq.heappop(heap)
        parent[i1] = next_id
        parent[i2] = next_id
        binary[i2] = 1
        heapq.heappush(heap, (c1 + c2, next_id, next_id))
        next_id += 1
    root = heap[0][2]
    for i in range(n):
        code: List[int] = []
        points: List[int] = []
        node = i
        while node != root:
            code.append(int(binary[node]))
            node = int(parent[node])
            points.append(node - n)
        code.reverse()
        points.reverse()
        vw = cache.entry_at(i)
        vw.code = code[:max_code_length]
        vw.points = points[:max_code_length]


def huffman_arrays(cache: VocabCache) -> tuple:
    """Dense (codes, points, mask) int32 arrays [V, L] for the device step.

    The reference walks per-word Java lists in the hot loop; the TPU
    formulation pads every word's path to the max length and masks — static
    shapes so the whole hierarchical-softmax round jits once.
    """
    n = len(cache)
    L = max((len(cache.entry_at(i).code) for i in range(n)), default=1) or 1
    codes = np.zeros((n, L), dtype=np.int32)
    points = np.zeros((n, L), dtype=np.int32)
    mask = np.zeros((n, L), dtype=np.float32)
    for i in range(n):
        vw = cache.entry_at(i)
        k = len(vw.code)
        codes[i, :k] = vw.code
        points[i, :k] = vw.points
        mask[i, :k] = 1.0
    return codes, points, mask


def unigram_table(cache: VocabCache, power: float = 0.75) -> np.ndarray:
    """Cumulative f^0.75 distribution for O(log V) negative sampling via
    searchsorted (reference: InMemoryLookupTable's 100M-entry unigram table —
    replaced by an exact CDF, which is both smaller and unbiased)."""
    counts = cache.counts().astype(np.float64)
    probs = counts ** power
    probs /= probs.sum()
    return np.cumsum(probs)


def unigram_int_table(cache: VocabCache, power: float = 0.75,
                      size: int = 1 << 20) -> np.ndarray:
    """Power-of-two int32 negative-sampling table: word i occupies a number
    of slots proportional to f_i^power (reference: InMemoryLookupTable's
    1e8-entry table; sized 2^20 here so a device draw is
    ``random_bits & (size-1)`` + one gather — measured ~20× cheaper per
    round than searchsorted over the exact CDF on TPU, see BASELINE.md
    round-3 Word2Vec audit). Words with probability < 1/size get no slot —
    the same truncation the reference's finite table applies."""
    assert size & (size - 1) == 0, "size must be a power of two"
    counts = cache.counts().astype(np.float64)
    if counts.size == 0 or counts.sum() <= 0:
        raise ValueError("empty vocabulary after pruning — cannot build "
                         "the negative-sampling table")
    probs = counts ** power
    probs /= probs.sum()
    alloc = np.floor(probs * size).astype(np.int64)
    shortfall = size - alloc.sum()
    if shortfall > 0:   # largest-remainder apportionment
        frac = probs * size - alloc
        alloc[np.argsort(-frac)[:shortfall]] += 1
    return np.repeat(np.arange(len(counts), dtype=np.int32), alloc)


def subsample_keep_probs(cache: VocabCache, sampling: float) -> np.ndarray:
    """Per-word keep probability for frequent-word subsampling (the canonical
    word2vec formula the reference applies in SkipGram.learnSequence:
    keep = (sqrt(f/(t*N)) + 1) * (t*N)/f, clipped to [0,1])."""
    if sampling <= 0:
        return np.ones(len(cache), dtype=np.float64)
    counts = cache.counts().astype(np.float64)
    total = counts.sum()
    ratio = sampling * total / np.maximum(counts, 1.0)
    keep = np.sqrt(ratio) + ratio
    return np.clip(keep, 0.0, 1.0)
