"""GloVe: global-vectors training over a co-occurrence matrix.

Reference: deeplearning4j-nlp ``models/glove/Glove`` +
``AbstractCoOccurrences`` (SURVEY §2.3 NLP row) — co-occurrence counting
with 1/distance weighting inside a symmetric window, then AdaGrad descent
on the weighted least-squares objective

    J = Σ_ij f(X_ij) (w_i·w̃_j + b_i + b̃_j − log X_ij)²,
    f(x) = min(1, (x/x_max)^alpha).

TPU-native structure (same split as Word2Vec's device-corpus path):

- co-occurrence accumulation happens on the HOST, vectorized per sentence
  chunk with one ``np.unique`` aggregation per chunk (the reference shuffles
  this work across RoundRobin worker threads; one vectorized pass replaces
  them);
- the nonzero triplets upload ONCE, and training runs as a ``lax.scan`` of
  fused batched rounds — gather rows → residual → AdaGrad scatter-update —
  with all four parameter tables (w, w̃, b, b̃) and their AdaGrad
  accumulators donated on device;
- like the reference, the final word vector is ``w + w̃``.
"""

from __future__ import annotations

import functools
import time
from typing import List, Optional, Sequence

import numpy as np

from ..common import xprof
from .lookup_table import InMemoryLookupTable
from .text import (CollectionSentenceIterator, DefaultTokenizerFactory,
                   SentenceIterator, TokenizerFactory)
from .vocab import VocabCache, VocabConstructor
from .word2vec import WordVectors


class Glove(WordVectors):
    MAX_BLOCK_ROUNDS = 64

    class Builder:
        def __init__(self):
            self._kw = {}
            self._iter = None
            self._tok: TokenizerFactory = DefaultTokenizerFactory()

        def min_word_frequency(self, v): self._kw["min_word_frequency"] = v; return self
        def layer_size(self, v): self._kw["layer_size"] = v; return self
        def window_size(self, v): self._kw["window"] = v; return self
        def learning_rate(self, v): self._kw["learning_rate"] = v; return self
        def epochs(self, v): self._kw["epochs"] = v; return self
        def x_max(self, v): self._kw["x_max"] = v; return self
        def alpha(self, v): self._kw["alpha"] = v; return self
        def batch_size(self, v): self._kw["batch_size"] = v; return self
        def seed(self, v): self._kw["seed"] = v; return self
        def symmetric(self, v): self._kw["symmetric"] = v; return self
        def shuffle(self, v): self._kw["shuffle"] = v; return self

        def iterate(self, it):
            if isinstance(it, (list, tuple)):
                it = CollectionSentenceIterator(it)
            self._iter = it
            return self

        def tokenizer_factory(self, tf):
            self._tok = tf
            return self

        def build(self) -> "Glove":
            g = Glove(**self._kw)
            g._sentence_iter = self._iter
            g._tokenizer = self._tok
            return g

    @staticmethod
    def builder() -> "Glove.Builder":
        return Glove.Builder()

    def __init__(self, *, layer_size: int = 100, window: int = 15,
                 learning_rate: float = 0.05, epochs: int = 5,
                 x_max: float = 100.0, alpha: float = 0.75,
                 min_word_frequency: int = 5, batch_size: int = 8192,
                 seed: int = 42, symmetric: bool = True,
                 shuffle: bool = True):
        self.layer_size = layer_size
        self.window = window
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.x_max = x_max
        self.alpha = alpha
        self.min_word_frequency = min_word_frequency
        self.batch_size = batch_size
        self.seed = seed
        self.symmetric = symmetric
        self.shuffle = shuffle
        self._sentence_iter: Optional[SentenceIterator] = None
        self._tokenizer: TokenizerFactory = DefaultTokenizerFactory()
        self.words_per_sec = 0.0
        self.last_loss = 0.0
        super().__init__(VocabCache(), InMemoryLookupTable(0, layer_size))

    # -- corpus plumbing (mirrors Word2Vec) -------------------------------
    def set_sentence_iterator(self, it) -> None:
        if isinstance(it, (list, tuple)):
            it = CollectionSentenceIterator(it)
        self._sentence_iter = it

    def _token_stream(self):
        assert self._sentence_iter is not None, "no corpus"
        self._sentence_iter.reset()
        for sentence in self._sentence_iter:
            yield self._tokenizer.create(sentence).get_tokens()

    def build_vocab(self, token_seqs) -> None:
        self.vocab = VocabConstructor(self.min_word_frequency).build(
            token_seqs)
        self.lookup_table = InMemoryLookupTable(
            len(self.vocab), self.layer_size, seed=self.seed)

    # -- co-occurrence counting (host, vectorized) ------------------------
    def co_occurrences(self, corpus: List[np.ndarray]):
        """Aggregate weighted counts over the corpus. Returns
        (rows, cols, counts) for the upper/whole matrix depending on
        ``symmetric`` convention: the reference accumulates both (i,j) and
        (j,i); we do the same so each row sees its full context."""
        V = len(self.vocab)
        W = self.window
        offs = np.arange(1, W + 1)
        weights = 1.0 / offs
        CHUNK = 4096
        keys_parts, vals_parts = [], []
        for s0 in range(0, len(corpus), CHUNK):
            chunk = corpus[s0:s0 + CHUNK]
            kk, vv = [], []
            for ids in chunk:
                n = ids.size
                if n < 2:
                    continue
                for d, wgt in zip(offs, weights):
                    if d >= n:
                        break
                    a, b = ids[:-d].astype(np.int64), ids[d:].astype(np.int64)
                    kk.append(a * V + b)
                    vv.append(np.full(a.size, wgt, np.float64))
                    kk.append(b * V + a)
                    vv.append(np.full(a.size, wgt, np.float64))
            if not kk:
                continue
            keys = np.concatenate(kk)
            vals = np.concatenate(vv)
            uk, inv = np.unique(keys, return_inverse=True)
            sums = np.zeros(uk.size, np.float64)
            np.add.at(sums, inv, vals)
            keys_parts.append(uk)
            vals_parts.append(sums)
        if not keys_parts:
            return (np.empty(0, np.int32),) * 2 + (np.empty(0, np.float32),)
        keys = np.concatenate(keys_parts)
        vals = np.concatenate(vals_parts)
        uk, inv = np.unique(keys, return_inverse=True)
        sums = np.zeros(uk.size, np.float64)
        np.add.at(sums, inv, vals)
        return ((uk // V).astype(np.int32), (uk % V).astype(np.int32),
                sums.astype(np.float32))

    # -- device training --------------------------------------------------
    def _make_block(self):
        import jax
        import jax.numpy as jnp
        from jax import lax

        lr = float(self.learning_rate)

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
        def block(w, wc, b, bc, gw, gwc, gb, gbc, cols):
            def body(carry, inp):
                w, wc, b, bc, gw, gwc, gb, gbc = carry
                i, j, logx, fw, pm = inp
                wi = w[i]
                wj = wc[j]
                diff = (jnp.einsum("bd,bd->b", wi, wj) + b[i] + bc[j]
                        - logx)                          # [B]
                fdiff = fw * diff * pm
                loss = 0.5 * (fdiff * diff).sum()
                # AdaGrad (reference: Glove uses AdaGrad with lr 0.05)
                g_wi = fdiff[:, None] * wj
                g_wj = fdiff[:, None] * wi
                gw = gw.at[i].add(g_wi * g_wi)
                gwc = gwc.at[j].add(g_wj * g_wj)
                gb = gb.at[i].add(fdiff * fdiff)
                gbc = gbc.at[j].add(fdiff * fdiff)
                w = w.at[i].add(-lr * g_wi / jnp.sqrt(gw[i] + 1e-8))
                wc = wc.at[j].add(-lr * g_wj / jnp.sqrt(gwc[j] + 1e-8))
                b = b.at[i].add(-lr * fdiff / jnp.sqrt(gb[i] + 1e-8))
                bc = bc.at[j].add(-lr * fdiff / jnp.sqrt(gbc[j] + 1e-8))
                return (w, wc, b, bc, gw, gwc, gb, gbc), loss
            carry, losses = lax.scan(
                body, (w, wc, b, bc, gw, gwc, gb, gbc), cols)
            return carry + (losses.mean(),)

        return xprof.register_jit("nlp/glove_block", block,
                                  donate=tuple(range(8)))

    def fit(self) -> None:
        import jax
        import jax.numpy as jnp

        if len(self.vocab) == 0:
            self.build_vocab(self._token_stream())
            if len(self.vocab) == 0:
                raise ValueError("empty vocabulary after pruning")
        corpus = []
        for tokens in self._token_stream():
            ids = [self.vocab.index_of(t) for t in tokens]
            ids = np.asarray([i for i in ids if i >= 0], dtype=np.int32)
            if ids.size:
                corpus.append(ids)
        total_words = sum(c.size for c in corpus)

        rows, cols_, counts = self.co_occurrences(corpus)
        nnz = rows.size
        if nnz == 0:
            raise ValueError("no co-occurrences — corpus too small")
        logx = np.log(np.maximum(counts, 1e-12)).astype(np.float32)
        fw = np.minimum(1.0, (counts / self.x_max) ** self.alpha) \
            .astype(np.float32)

        V, D, B = len(self.vocab), self.layer_size, self.batch_size
        rng = np.random.default_rng(self.seed)
        w = jnp.asarray(((rng.random((V, D)) - 0.5) / D).astype(np.float32))
        wc = jnp.asarray(((rng.random((V, D)) - 0.5) / D).astype(np.float32))
        b = jnp.zeros((V,), jnp.float32)
        bc = jnp.zeros((V,), jnp.float32)
        gw = jnp.full((V, D), 1e-8, jnp.float32)
        gwc = jnp.full((V, D), 1e-8, jnp.float32)
        gb = jnp.full((V,), 1e-8, jnp.float32)
        gbc = jnp.full((V,), 1e-8, jnp.float32)

        block = self._make_block()
        span = B * self.MAX_BLOCK_ROUNDS
        t0 = time.perf_counter()
        losses = []
        for _ep in range(self.epochs):
            order = rng.permutation(nnz) if self.shuffle else np.arange(nnz)
            pad = (-nnz) % span
            # filler indices are masked out by pm; np.resize cycles when
            # pad > nnz (tiny co-occurrence sets)
            idx = (np.concatenate([order, np.resize(order, pad)])
                   if pad else order)
            pm_full = np.ones(idx.size, np.float32)
            if pad:
                pm_full[nnz:] = 0.0
            R_total = idx.size // B
            i3 = rows[idx].reshape(R_total, B)
            j3 = cols_[idx].reshape(R_total, B)
            lx3 = logx[idx].reshape(R_total, B)
            fw3 = fw[idx].reshape(R_total, B)
            pm3 = pm_full.reshape(R_total, B)
            for r0 in range(0, R_total, self.MAX_BLOCK_ROUNDS):
                sl = slice(r0, r0 + self.MAX_BLOCK_ROUNDS)
                w, wc, b, bc, gw, gwc, gb, gbc, loss = block(
                    w, wc, b, bc, gw, gwc, gb, gbc,
                    (i3[sl], j3[sl], lx3[sl], fw3[sl], pm3[sl]))
                losses.append(loss)
        last = np.asarray(jnp.stack(losses[-20:])) if losses else \
            np.zeros(1, np.float32)
        dt = time.perf_counter() - t0
        self.words_per_sec = total_words * self.epochs / max(dt, 1e-9)
        self.last_loss = float(last.mean())
        # reference convention: final vectors are w + w̃
        self.lookup_table.syn0 = np.asarray(w) + np.asarray(wc)
        self._w = np.asarray(w)
        self._wc = np.asarray(wc)
        self._bias = np.asarray(b)
        self._bias_c = np.asarray(bc)
