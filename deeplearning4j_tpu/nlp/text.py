"""Tokenization + sentence iteration SPI.

TPU rebuild of the reference's text-pipeline SPIs (reference layout:
deeplearning4j-nlp ``text/tokenization/tokenizer`` and
``text/sentenceiterator`` — ``TokenizerFactory``, ``DefaultTokenizer``,
``CommonPreprocessor``, ``SentenceIterator`` / ``LineSentenceIterator`` /
``CollectionSentenceIterator``). These run on host (pure Python) — they feed
the vectorized pair-generation stage, which feeds the jitted device step; the
per-token work is trivial and never belongs on the accelerator.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Callable, Iterable, Iterator, List, Optional, Sequence


class TokenPreProcess:
    """SPI: normalize a single token (reference: TokenPreProcess)."""

    def pre_process(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/digits (reference: CommonPreprocessor)."""

    _PUNCT = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token).lower()


class LowCasePreProcessor(TokenPreProcess):
    def pre_process(self, token: str) -> str:
        return token.lower()


class Tokenizer:
    """One sentence → token stream (reference: Tokenizer interface)."""

    def __init__(self, tokens: List[str],
                 pre_processor: Optional[TokenPreProcess] = None):
        self._tokens = tokens
        self._pre = pre_processor

    def get_tokens(self) -> List[str]:
        if self._pre is None:
            return list(self._tokens)
        out = [self._pre.pre_process(t) for t in self._tokens]
        return [t for t in out if t]

    def count_tokens(self) -> int:
        return len(self.get_tokens())

    def __iter__(self) -> Iterator[str]:
        return iter(self.get_tokens())


class TokenizerFactory:
    """SPI: sentence → Tokenizer (reference: TokenizerFactory)."""

    def __init__(self) -> None:
        self._pre: Optional[TokenPreProcess] = None

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self._pre = pre

    def create(self, sentence: str) -> Tokenizer:
        raise NotImplementedError


class DefaultTokenizerFactory(TokenizerFactory):
    """Whitespace split (reference: DefaultTokenizerFactory wraps a
    StringTokenizer over whitespace)."""

    def create(self, sentence: str) -> Tokenizer:
        return Tokenizer(sentence.split(), self._pre)


class NGramTokenizerFactory(TokenizerFactory):
    """Emit all n-grams for n in [min_n, max_n] joined by spaces
    (reference: NGramTokenizerFactory)."""

    def __init__(self, min_n: int, max_n: int):
        super().__init__()
        self.min_n, self.max_n = min_n, max_n

    def create(self, sentence: str) -> Tokenizer:
        base = Tokenizer(sentence.split(), self._pre).get_tokens()
        grams: List[str] = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(len(base) - n + 1):
                grams.append(" ".join(base[i:i + n]))
        return Tokenizer(grams, None)


class SentenceIterator:
    """SPI: stream of sentences, restartable (reference: SentenceIterator).

    Subclasses implement ``__iter__``; ``reset()`` restarts the stream so the
    vocab-construction pass and each training epoch can re-scan the corpus.
    """

    def __iter__(self) -> Iterator[str]:
        raise NotImplementedError

    def reset(self) -> None:  # default: __iter__ builds a fresh iterator
        pass


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: Sequence[str]):
        self._sentences = list(sentences)

    def __iter__(self) -> Iterator[str]:
        return iter(self._sentences)


class LineSentenceIterator(SentenceIterator):
    """One sentence per line from a text file (reference:
    LineSentenceIterator / BasicLineIterator)."""

    def __init__(self, path: str | Path):
        self._path = Path(path)

    def __iter__(self) -> Iterator[str]:
        with open(self._path, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line


class FileSentenceIterator(SentenceIterator):
    """All files under a directory, one sentence per line (reference:
    FileSentenceIterator)."""

    def __init__(self, root: str | Path):
        self._root = Path(root)

    def __iter__(self) -> Iterator[str]:
        files = sorted(p for p in self._root.rglob("*") if p.is_file())
        for p in files:
            yield from LineSentenceIterator(p)


class LabelAwareIterator(SentenceIterator):
    """Sentence stream with a document label per sentence, for
    ParagraphVectors (reference: LabelAwareSentenceIterator /
    LabelsSource)."""

    def __init__(self, sentences: Sequence[str],
                 labels: Optional[Sequence[str]] = None):
        if labels is not None and len(labels) != len(sentences):
            raise ValueError("labels and sentences must align")
        self._sentences = list(sentences)
        self._labels = (list(labels) if labels is not None
                        else [f"DOC_{i}" for i in range(len(sentences))])

    def __iter__(self) -> Iterator[str]:
        return iter(self._sentences)

    def labeled(self) -> Iterator[tuple]:
        return iter(zip(self._labels, self._sentences))

    @property
    def labels(self) -> List[str]:
        return list(self._labels)
