"""DeepWalk / Node2Vec: vertex embeddings from random walks.

Reference: deeplearning4j-graph ``models/deepwalk/DeepWalk`` +
``iterator/RandomWalkIterator`` (SURVEY §2.3 NLP row). The construction is
walks-as-sentences: sample random walks over the graph, then train the
skip-gram engine on them — which here means the walks feed straight into
the TPU device-corpus Word2Vec path. Node2Vec generalizes the walk
distribution with the (p, q) second-order bias (Grover & Leskovec); p=q=1
reduces to DeepWalk's uniform walks.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .word2vec import Word2Vec


class Graph:
    """Adjacency-list graph (reference: org.deeplearning4j.graph.graph.Graph)."""

    def __init__(self, n_vertices: int, directed: bool = False):
        self.n = n_vertices
        self.directed = directed
        self._adj: List[List[int]] = [[] for _ in range(n_vertices)]

    def add_edge(self, a: int, b: int) -> None:
        self._adj[a].append(b)
        if not self.directed:
            self._adj[b].append(a)

    def neighbors(self, v: int) -> List[int]:
        return self._adj[v]

    def num_vertices(self) -> int:
        return self.n


def random_walks(graph: Graph, num_walks: int, walk_length: int,
                 seed: int = 42, p: float = 1.0, q: float = 1.0
                 ) -> List[List[int]]:
    """``num_walks`` walks from every vertex. p/q are node2vec's return /
    in-out parameters; transition weight to x from (prev t, cur v):
    1/p if x == t, 1 if x adjacent to t, 1/q otherwise."""
    rng = np.random.default_rng(seed)
    walks = []
    biased = not (p == 1.0 and q == 1.0)
    adj_sets = [set(a) for a in graph._adj] if biased else None
    for _ in range(num_walks):
        for start in range(graph.num_vertices()):
            if not graph.neighbors(start):
                continue
            walk = [start]
            while len(walk) < walk_length:
                cur = walk[-1]
                nbrs = graph.neighbors(cur)
                if not nbrs:
                    break
                if len(walk) == 1 or not biased:
                    nxt = nbrs[rng.integers(len(nbrs))]
                else:
                    prev = walk[-2]
                    w = np.asarray(
                        [1.0 / p if x == prev
                         else (1.0 if x in adj_sets[prev] else 1.0 / q)
                         for x in nbrs])
                    w /= w.sum()
                    nxt = nbrs[rng.choice(len(nbrs), p=w)]
                walk.append(int(nxt))
            walks.append(walk)
    return walks


class DeepWalk:
    """reference: DeepWalk.Builder().windowSize(..).vectorSize(..).build()
    then fit over a walk iterator — here ``fit(graph)`` samples the walks
    and trains in one call."""

    class Builder:
        def __init__(self):
            self._kw = {}

        def window_size(self, v): self._kw["window_size"] = v; return self
        def vector_size(self, v): self._kw["vector_size"] = v; return self
        def walk_length(self, v): self._kw["walk_length"] = v; return self
        def num_walks(self, v): self._kw["num_walks"] = v; return self
        def learning_rate(self, v): self._kw["learning_rate"] = v; return self
        def epochs(self, v): self._kw["epochs"] = v; return self
        def negative_sample(self, v): self._kw["negative"] = int(v); return self
        def seed(self, v): self._kw["seed"] = v; return self

        def build(self) -> "DeepWalk":
            return DeepWalk(**self._kw)

    @staticmethod
    def builder() -> "DeepWalk.Builder":
        return DeepWalk.Builder()

    # node2vec parameters; DeepWalk keeps the uniform walk
    p = 1.0
    q = 1.0

    def __init__(self, window_size: int = 5, vector_size: int = 64,
                 walk_length: int = 40, num_walks: int = 10,
                 learning_rate: float = 0.025, epochs: int = 1,
                 negative: int = 5, seed: int = 42):
        self.window_size = window_size
        self.vector_size = vector_size
        self.walk_length = walk_length
        self.num_walks = num_walks
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.negative = negative
        self.seed = seed
        self._w2v: Optional[Word2Vec] = None

    def fit(self, graph: Graph) -> "DeepWalk":
        walks = random_walks(graph, self.num_walks, self.walk_length,
                             seed=self.seed, p=self.p, q=self.q)
        sentences = [" ".join(str(v) for v in walk) for walk in walks]
        w2v = Word2Vec(min_word_frequency=1, layer_size=self.vector_size,
                       window=self.window_size, negative=self.negative,
                       learning_rate=self.learning_rate, epochs=self.epochs,
                       batch_size=1024, seed=self.seed)
        w2v.set_sentence_iterator(sentences)
        w2v.fit()
        self._w2v = w2v
        return self

    # -- queries ----------------------------------------------------------
    def get_vertex_vector(self, v: int) -> np.ndarray:
        assert self._w2v is not None, "call fit(graph) first"
        return self._w2v.get_word_vector(str(v))

    def similarity(self, a: int, b: int) -> float:
        assert self._w2v is not None, "call fit(graph) first"
        return self._w2v.similarity(str(a), str(b))

    def verticies_nearest(self, v: int, top_n: int = 10) -> List[int]:
        assert self._w2v is not None, "call fit(graph) first"
        return [int(w) for w in self._w2v.words_nearest(str(v), top_n)]

    vertices_nearest = verticies_nearest


class Node2Vec(DeepWalk):
    """Grover & Leskovec's biased-walk generalization; the reference repo
    carries DeepWalk only — node2vec is the standard successor with the
    identical training half, so it rides the same engine."""

    def __init__(self, *args, p: float = 1.0, q: float = 1.0, **kw):
        super().__init__(*args, **kw)
        self.p = p
        self.q = q
