"""FastText: subword-enriched word vectors.

Reference: dl4j-nlp ``models/fasttext/FastText`` (SURVEY §2.3 NLP row) — a
thin wrapper around the external fastText C++ library. No external binary
here: the skip-gram-with-subwords training procedure (Bojanowski et al.) is
implemented natively on the existing fused device rounds:

- every vocab word expands to itself + its char n-grams (minn..maxn over
  ``<word>``), n-grams hashed into ``bucket`` extra table rows with
  fastText's FNV-1a variant;
- the input vector of a center word is the MEAN of its subword rows, and
  gradients spread back over those rows — exactly the shape of the engine's
  fused CBOW round (``ops/embeddings.cbow``), so training reuses it: the
  "context window" slot carries the center's subword ids, the "center"
  slot carries the context word (the skip-gram target), negatives come
  from the engine's on-device unigram table;
- out-of-vocabulary words get vectors from their n-grams alone — the
  fastText property the reference wrapper exposes via
  ``getWordVector`` on unseen words.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..common import xprof
from .lookup_table import InMemoryLookupTable
from .vocab import VocabConstructor
from .word2vec import SequenceVectors


def fasttext_hash(ngram: str) -> int:
    """fastText's FNV-1a over utf-8 bytes (Dictionary::hash); 32-bit
    wraparound made explicit with a mask."""
    h = 2166136261
    for byte in ngram.encode("utf-8"):
        h = ((h ^ byte) * 16777619) & 0xFFFFFFFF
    return h


def char_ngrams(word: str, minn: int, maxn: int) -> List[str]:
    w = f"<{word}>"
    out = []
    for n in range(minn, maxn + 1):
        if n > len(w):
            break
        for i in range(len(w) - n + 1):
            out.append(w[i:i + n])
    return out


class FastText(SequenceVectors):
    class Builder:
        def __init__(self):
            self._kw = {}
            self._iter = None

        def min_word_frequency(self, v): self._kw["min_word_frequency"] = v; return self
        def layer_size(self, v): self._kw["layer_size"] = v; return self
        def window_size(self, v): self._kw["window"] = v; return self
        def learning_rate(self, v): self._kw["learning_rate"] = v; return self
        def negative_sample(self, v): self._kw["negative"] = int(v); return self
        def epochs(self, v): self._kw["epochs"] = v; return self
        def batch_size(self, v): self._kw["batch_size"] = v; return self
        def seed(self, v): self._kw["seed"] = v; return self
        def bucket(self, v): self._kw["bucket"] = v; return self
        def minn(self, v): self._kw["minn"] = v; return self
        def maxn(self, v): self._kw["maxn"] = v; return self

        def iterate(self, it):
            self._iter = it
            return self

        def build(self) -> "FastText":
            ft = FastText(**self._kw)
            if self._iter is not None:
                ft.set_sentence_iterator(self._iter)
            return ft

    @staticmethod
    def builder() -> "FastText.Builder":
        return FastText.Builder()

    def __init__(self, *, bucket: int = 100_000, minn: int = 3,
                 maxn: int = 6, **kw):
        kw.setdefault("algorithm", "cbow")   # reuses the fused cbow round
        super().__init__(**kw)
        self.bucket = bucket
        self.minn = minn
        self.maxn = maxn
        self._sentence_iter = None
        self._subword_ids: Optional[np.ndarray] = None   # [V, G] padded
        self._subword_mask: Optional[np.ndarray] = None  # [V, G]

    # -- plumbing ---------------------------------------------------------
    def set_sentence_iterator(self, it) -> None:
        from .text import CollectionSentenceIterator

        if isinstance(it, (list, tuple)):
            it = CollectionSentenceIterator(it)
        self._sentence_iter = it

    def _token_stream(self):
        from .text import DefaultTokenizerFactory

        assert self._sentence_iter is not None, "no corpus"
        self._sentence_iter.reset()
        tok = DefaultTokenizerFactory()
        for sentence in self._sentence_iter:
            yield tok.create(sentence).get_tokens()

    def subword_row_ids(self, word: str, in_vocab_index: int = -1
                        ) -> List[int]:
        """Table rows for a word: its own row (if in vocab) + hashed
        n-gram rows living above the vocab block."""
        V = len(self.vocab)
        ids = [in_vocab_index] if in_vocab_index >= 0 else []
        for g in char_ngrams(word, self.minn, self.maxn):
            ids.append(V + fasttext_hash(g) % self.bucket)
        return ids

    def build_vocab(self, token_seqs) -> None:
        self.vocab = VocabConstructor(self.min_word_frequency).build(
            token_seqs)
        V = len(self.vocab)
        # syn0 covers vocab + n-gram buckets; syn1neg only needs the vocab
        # block (targets are words) but shares the table shape for the
        # fused round's donation contract
        self.lookup_table = InMemoryLookupTable(
            V + self.bucket, self.layer_size, seed=self.seed)
        self.lookup_table.reset_weights(False, True)
        sub = [self.subword_row_ids(w, i)
               for i, w in enumerate(self.vocab.words())]
        G = max(len(s) for s in sub) if sub else 1
        self._subword_ids = np.zeros((V, G), np.int32)
        self._subword_mask = np.zeros((V, G), np.float32)
        for i, s in enumerate(sub):
            self._subword_ids[i, :len(s)] = s
            self._subword_mask[i, :len(s)] = 1.0

    def _make_window_block(self, hs_dev=None, ntable_dev=None):
        """Device FastText block (round 5): overrides the skip-gram
        windowed block builder so ``_train_windowed`` drives THIS block
        through its unchanged corpus-resident loop. Pairs come from the
        shared ``_pack_span`` dense packer; each pair trains the CBOW
        kernel with the CENTER's subword rows as the context window
        (device-resident [V, G] id/mask tables, gathered per round) and
        the CONTEXT word as target — the same math as the host stream,
        minus the per-pair host subword expansion that capped it at the
        10–20k words/s class."""
        import functools

        import jax
        import jax.numpy as jnp
        from jax import lax

        from ..ops import embeddings as E
        from .word2vec import _pack_span, _pool_negs

        if self.use_hs or hs_dev is not None:
            raise ValueError("FastText trains with negative sampling only")
        V, K, W = len(self.vocab), self.negative, self.window
        B = self._round_pairs
        R = self.MAX_BLOCK_ROUNDS
        S = self._window_span
        C = -(-(S * 2 * W) // B) * B
        lab = jnp.zeros((B, 1 + K), jnp.float32).at[:, 0].set(1.0)
        self._win_negpool = self._build_negpool(ntable_dev, B * K)
        sub_ids = jnp.asarray(self._subword_ids)
        sub_mask = jnp.asarray(self._subword_mask)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def block(syn0, syn1, ids, sent, n_valid, negpool, p0, lr01, key,
                  blk_id):
            key = jax.random.fold_in(key, blk_id)
            packed_c, packed_x, count = _pack_span(
                ids, sent, n_valid, p0, S, W, C, key)
            lr0, lr1 = lr01
            countf = jnp.maximum(count.astype(jnp.float32), 1.0)

            def cond(st):
                return st[0] * B < count

            def body(st):
                r, s0, s1, lsum, wsum = st
                c = lax.dynamic_slice(packed_c, (r * B,), (B,))
                x = lax.dynamic_slice(packed_x, (r * B,), (B,))
                pm = ((lax.broadcasted_iota(jnp.int32, (B,), 0) + r * B)
                      < count).astype(jnp.float32)
                lr = lr0 + (lr1 - lr0) * (r * B).astype(jnp.float32) / countf
                negs = _pool_negs(negpool, blk_id, r, B, K, V, x)
                tgt = jnp.concatenate([x[:, None], negs], axis=1)
                s0, s1, loss = E.cbow(s0, s1, sub_ids[c], sub_mask[c],
                                      tgt, lab, lr, pm, dense=False)
                return (r + 1, s0, s1, lsum + loss * pm.sum(),
                        wsum + pm.sum())

            init = (jnp.int32(0), syn0, syn1, jnp.float32(0.0),
                    jnp.float32(0.0))
            _, syn0, syn1, lsum, wsum = lax.while_loop(cond, body, init)
            return (syn0, syn1, lsum / jnp.maximum(wsum, 1.0), wsum)

        return xprof.register_jit("nlp/fasttext_block", block,
                                  donate=(0, 1))

    def fit(self) -> None:
        if len(self.vocab) == 0 or self.lookup_table.syn0 is None:
            self.build_vocab(self._token_stream())
            if len(self.vocab) == 0:
                raise ValueError("empty vocabulary after pruning")
        corpus = self._encode_corpus(self._token_stream())

        if getattr(self, "device_corpus", True) and not self.use_hs \
                and self.mesh is None:
            # device-windowed path: _train_windowed's skip-gram branch
            # drives the overridden _make_window_block above. algorithm is
            # temporarily "skipgram" so the loop picks the PAIR machinery
            # (sizing + branch); the constructor default stays "cbow" for
            # the host fallback's stream format.
            old = self.algorithm
            self.algorithm = "skipgram"
            try:
                return self._train_windowed(corpus)
            finally:
                self.algorithm = old

        def stream(rng, keep):
            # skip-gram pairs; the cbow-round "window" is the CENTER's
            # subword set, the cbow-round "center" is the CONTEXT word
            for ids in corpus:
                pairs = self._sentence_pairs(ids, rng, keep)
                if pairs is None:
                    continue
                centers, contexts = pairs
                yield (ids.size, contexts,
                       self._subword_ids[centers],
                       self._subword_mask[centers])

        self._train_encoded(corpus, stream_factory=stream)

    # -- queries (subword composition) ------------------------------------
    def get_word_vector(self, word: str) -> np.ndarray:
        idx = self.vocab.index_of(word)
        rows = self.subword_row_ids(word, idx)
        if not rows:
            raise KeyError(f"cannot build a vector for {word!r}")
        syn0 = np.asarray(self.lookup_table.syn0)
        return syn0[np.asarray(rows, np.int64)].mean(axis=0)

    def get_word_vector_matrix(self) -> np.ndarray:
        """Composed [V, D] export matrix (subword means) — overrides the
        base's raw-syn0 export protocol."""
        syn0 = np.asarray(self.lookup_table.syn0)
        num = (syn0[self._subword_ids.reshape(-1)]
               .reshape(*self._subword_ids.shape, -1)
               * self._subword_mask[..., None]).sum(axis=1)
        return num / np.maximum(self._subword_mask.sum(axis=1), 1.0)[:, None]

    def words_nearest(self, word_or_vec, top_n: int = 10) -> List[str]:
        if isinstance(word_or_vec, str):
            vec = self.get_word_vector(word_or_vec)
            exclude = {self.vocab.index_of(word_or_vec)}
        else:
            vec = np.asarray(word_or_vec, np.float32)
            exclude = set()
        mat = self.get_word_vector_matrix()
        mat = mat / np.maximum(np.linalg.norm(mat, axis=1, keepdims=True),
                               1e-12)
        v = vec / max(np.linalg.norm(vec), 1e-12)
        order = np.argsort(-(mat @ v))
        out = []
        for idx in order:
            if int(idx) in exclude:
                continue
            out.append(self.vocab.word_for(int(idx)))
            if len(out) == top_n:
                break
        return out
