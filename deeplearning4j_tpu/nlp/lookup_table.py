"""In-memory embedding tables (reference: InMemoryLookupTable).

Holds ``syn0`` (input vectors), ``syn1`` (hierarchical-softmax inner nodes)
and ``syn1neg`` (negative-sampling output vectors) as device arrays during
training — the fused rounds in ``ops/embeddings.py`` update them in place via
buffer donation — and exposes numpy views for queries/serde.

Weight init matches the reference's ``resetWeights``: syn0 ~ U(-0.5, 0.5)/d
from the configured seed, syn1/syn1neg zeros.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class InMemoryLookupTable:
    def __init__(self, vocab_size: int, vector_length: int,
                 seed: int = 42, dtype: str = "float32"):
        self.vocab_size = vocab_size
        self.vector_length = vector_length
        self.seed = seed
        self.dtype = np.dtype(dtype)
        self.syn0: Optional[np.ndarray] = None
        self.syn1: Optional[np.ndarray] = None
        self.syn1neg: Optional[np.ndarray] = None

    def reset_weights(self, use_hs: bool, use_neg: bool) -> None:
        rng = np.random.default_rng(self.seed)
        d = self.vector_length
        self.syn0 = ((rng.random((self.vocab_size, d)) - 0.5) / d) \
            .astype(self.dtype)
        self.syn1 = (np.zeros((self.vocab_size, d), dtype=self.dtype)
                     if use_hs else None)
        self.syn1neg = (np.zeros((self.vocab_size, d), dtype=self.dtype)
                        if use_neg else None)

    def vector(self, index: int) -> np.ndarray:
        return np.asarray(self.syn0[index])

    def normalized(self) -> np.ndarray:
        """Row-normalized syn0 for cosine queries (computed lazily by
        callers; not cached — training mutates syn0)."""
        w = np.asarray(self.syn0, dtype=np.float32)
        norms = np.linalg.norm(w, axis=1, keepdims=True)
        return w / np.maximum(norms, 1e-12)
