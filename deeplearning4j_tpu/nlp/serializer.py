"""WordVectorSerializer: interchange formats for word vectors.

Rebuild of the reference's
``loader/WordVectorSerializer`` covering the two interchange formats every
word2vec toolchain speaks:

- **text** ("Google txt" / glove-style): optional ``V D`` header line, then
  one ``word f1 f2 ... fD`` line per word;
- **binary** (Google ``word2vec.c`` bin): ``V D\\n`` ASCII header, then per
  word ``word<space>`` followed by D little-endian float32s.

plus ``write_word2vec_model``/``read_word2vec_model``: a zip container with
the full training state (vocab counts, syn0/syn1/syn1neg, config) so a fit
can be resumed — the role of the reference's ``writeWord2VecModel`` zip
(syn0.txt/syn1.txt/codes.txt/huffman.txt/config.json).
"""

from __future__ import annotations

import io
import json
import zipfile
from pathlib import Path
from typing import Union

import numpy as np

from .lookup_table import InMemoryLookupTable
from .vocab import VocabCache, VocabWord, build_huffman
from .word2vec import Word2Vec, WordVectors

PathLike = Union[str, Path]

_FORMAT_VERSION = 1


# -- flat vector formats --------------------------------------------------

def write_word_vectors(model: WordVectors, path: PathLike,
                       binary: bool = False, header: bool = True) -> None:
    # get_word_vector_matrix is the export protocol: composed models
    # (FastText subword means) override it; the base returns raw syn0
    syn0 = np.asarray(model.get_word_vector_matrix(), dtype=np.float32)
    words = model.vocab.words()
    if binary:
        with open(path, "wb") as f:
            f.write(f"{len(words)} {syn0.shape[1]}\n".encode())
            for i, w in enumerate(words):
                f.write(w.encode("utf-8") + b" ")
                f.write(syn0[i].tobytes())
                f.write(b"\n")
    else:
        with open(path, "w", encoding="utf-8") as f:
            if header:
                f.write(f"{len(words)} {syn0.shape[1]}\n")
            for i, w in enumerate(words):
                vec = " ".join(f"{x:.6g}" for x in syn0[i])
                f.write(f"{w} {vec}\n")


def read_word_vectors(path: PathLike, binary: bool = False) -> WordVectors:
    if binary:
        with open(path, "rb") as f:
            header = f.readline().decode().split()
            V, D = int(header[0]), int(header[1])
            vocab = VocabCache()
            syn0 = np.zeros((V, D), dtype=np.float32)
            for i in range(V):
                chars = []
                while True:
                    ch = f.read(1)
                    if ch == b" " or ch == b"":
                        break
                    if ch != b"\n":
                        chars.append(ch)
                word = b"".join(chars).decode("utf-8")
                syn0[i] = np.frombuffer(f.read(4 * D), dtype="<f4")
                nl = f.read(1)
                if nl not in (b"\n", b""):
                    f.seek(-1, io.SEEK_CUR)
                vocab.add(VocabWord(word, 1))
    else:
        with open(path, "r", encoding="utf-8") as f:
            lines = [ln.rstrip("\n") for ln in f if ln.strip()]
        first = lines[0].split()
        if len(first) == 2 and all(tok.isdigit() for tok in first):
            V, D = int(first[0]), int(first[1])
            lines = lines[1:]
        else:
            V, D = len(lines), len(first) - 1
        vocab = VocabCache()
        syn0 = np.zeros((V, D), dtype=np.float32)
        for i, ln in enumerate(lines):
            parts = ln.split(" ")
            vocab.add(VocabWord(parts[0], 1))
            syn0[i] = np.asarray(parts[1:], dtype=np.float32)
    table = InMemoryLookupTable(len(vocab), syn0.shape[1])
    table.syn0 = syn0
    return WordVectors(vocab, table)


# -- full-model zip container ---------------------------------------------

def write_word2vec_model(model: Word2Vec, path: PathLike) -> None:
    config = {
        "format_version": _FORMAT_VERSION,
        "layer_size": model.layer_size,
        "window": model.window,
        "learning_rate": model.learning_rate,
        "min_learning_rate": model.min_learning_rate,
        "negative": model.negative,
        "use_hierarchic_softmax": model.use_hs,
        "sampling": model.sampling,
        "min_word_frequency": model.min_word_frequency,
        "iterations": model.iterations,
        "epochs": model.epochs,
        "batch_size": model.batch_size,
        "seed": model.seed,
        "algorithm": model.algorithm,
    }
    vocab_rows = [{"word": model.vocab.entry_at(i).word,
                   "count": model.vocab.entry_at(i).count}
                  for i in range(len(model.vocab))]
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("config.json", json.dumps(config))
        z.writestr("vocab.json", json.dumps(vocab_rows))
        arrays = {"syn0": np.asarray(model.lookup_table.syn0)}
        if model.lookup_table.syn1 is not None:
            arrays["syn1"] = np.asarray(model.lookup_table.syn1)
        if model.lookup_table.syn1neg is not None:
            arrays["syn1neg"] = np.asarray(model.lookup_table.syn1neg)
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        z.writestr("tables.npz", buf.getvalue())


def read_word2vec_model(path: PathLike) -> Word2Vec:
    with zipfile.ZipFile(path, "r") as z:
        config = json.loads(z.read("config.json"))
        version = config.pop("format_version", None)
        if version != _FORMAT_VERSION:
            raise ValueError(
                f"unsupported word2vec model format version {version!r} "
                f"(supported: {_FORMAT_VERSION})")
        vocab_rows = json.loads(z.read("vocab.json"))
        npz = np.load(io.BytesIO(z.read("tables.npz")))
        model = Word2Vec(**config)
        vocab = VocabCache()
        for row in vocab_rows:
            vocab.add(VocabWord(row["word"], row["count"]))
        model.vocab = vocab
        if model.use_hs:
            build_huffman(model.vocab)
        table = InMemoryLookupTable(len(vocab), config["layer_size"],
                                    seed=config["seed"])
        table.syn0 = npz["syn0"]
        table.syn1 = npz["syn1"] if "syn1" in npz else None
        table.syn1neg = npz["syn1neg"] if "syn1neg" in npz else None
        model.lookup_table = table
        return model


def write_paragraph_vectors(model, path: PathLike) -> None:
    """ParagraphVectors zip container (reference
    ``WordVectorSerializer.writeParagraphVectors``): the word2vec payload
    plus the PV config (dm, train_word_vectors) and the doc-label list,
    so ``read_paragraph_vectors`` restores label lookups, nearest_labels,
    and infer_vector against the frozen tables."""
    config = {
        "format_version": _FORMAT_VERSION,
        "layer_size": model.layer_size,
        "window": model.window,
        "learning_rate": model.learning_rate,
        "min_learning_rate": model.min_learning_rate,
        "negative": model.negative,
        "use_hierarchic_softmax": model.use_hs,
        "sampling": model.sampling,
        "min_word_frequency": model.min_word_frequency,
        "iterations": model.iterations,
        "epochs": model.epochs,
        "batch_size": model.batch_size,
        "seed": model.seed,
        "dm": model.dm,
        "train_word_vectors": model.train_word_vectors,
    }
    vocab_rows = [{"word": model.vocab.entry_at(i).word,
                   "count": model.vocab.entry_at(i).count}
                  for i in range(len(model.vocab))]
    labels = [model.vocab.word_for(i) for i in model._label_ids]
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("config.json", json.dumps(config))
        z.writestr("vocab.json", json.dumps(vocab_rows))
        z.writestr("labels.json", json.dumps(labels))
        arrays = {"syn0": np.asarray(model.lookup_table.syn0)}
        if model.lookup_table.syn1 is not None:
            arrays["syn1"] = np.asarray(model.lookup_table.syn1)
        if model.lookup_table.syn1neg is not None:
            arrays["syn1neg"] = np.asarray(model.lookup_table.syn1neg)
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        z.writestr("tables.npz", buf.getvalue())


def read_paragraph_vectors(path: PathLike):
    from .paragraph_vectors import ParagraphVectors

    with zipfile.ZipFile(path, "r") as z:
        config = json.loads(z.read("config.json"))
        version = config.pop("format_version", None)
        if version != _FORMAT_VERSION:
            raise ValueError(
                f"unsupported paragraph-vectors format version {version!r} "
                f"(supported: {_FORMAT_VERSION})")
        vocab_rows = json.loads(z.read("vocab.json"))
        labels = json.loads(z.read("labels.json"))
        npz = np.load(io.BytesIO(z.read("tables.npz")))
        model = ParagraphVectors(**config)
        vocab = VocabCache()
        for row in vocab_rows:
            vocab.add(VocabWord(row["word"], row["count"]))
        model.vocab = vocab
        if model.use_hs:
            build_huffman(model.vocab)
        table = InMemoryLookupTable(len(vocab), config["layer_size"],
                                    seed=config["seed"])
        table.syn0 = npz["syn0"]
        table.syn1 = npz["syn1"] if "syn1" in npz else None
        table.syn1neg = npz["syn1neg"] if "syn1neg" in npz else None
        model.lookup_table = table
        model._label_ids = [vocab.index_of(l) for l in labels]
        model._special_tokens = labels
        return model


# reference spellings
writeParagraphVectors = write_paragraph_vectors
readParagraphVectors = read_paragraph_vectors
