"""ParagraphVectors (doc2vec): PV-DM and PV-DBOW.

Rebuild of the reference's ``models/paragraphvectors/ParagraphVectors`` with
its two sequence-learning algorithms (reference:
``models/embeddings/learning/impl/sequence/{DM,DBOW}.java``):

- **PV-DBOW** (``DBOW``): the document's label vector is the *input* row and
  every word of the document is a prediction target — exactly the skip-gram
  round with the label id as "center", so it reuses the fused ``skipgram``
  op unchanged.
- **PV-DM** (``DM``): the label vector joins the context-window average that
  predicts the center word — the CBOW round with one extra always-on context
  column carrying the label id.

Labels live in the SAME vocab/syn0 table as words (the reference adds them
as special VocabWords exempt from frequency pruning); ``infer_vector`` runs
gradient steps on a fresh row with frozen word/output tables, matching the
reference's inference-vector mode of the fused kernels (libnd4j sg_cb
``infVector`` path) — here it is simply ``jax.grad`` wrt the one vector.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np

from .text import DefaultTokenizerFactory, LabelAwareIterator, TokenizerFactory
from .word2vec import SequenceVectors


class ParagraphVectors(SequenceVectors):
    class Builder:
        def __init__(self) -> None:
            self._kw = {}
            self._iter: Optional[LabelAwareIterator] = None
            self._tok: TokenizerFactory = DefaultTokenizerFactory()

        def min_word_frequency(self, v): self._kw["min_word_frequency"] = v; return self
        def iterations(self, v): self._kw["iterations"] = v; return self
        def epochs(self, v): self._kw["epochs"] = v; return self
        def layer_size(self, v): self._kw["layer_size"] = v; return self
        def seed(self, v): self._kw["seed"] = v; return self
        def window_size(self, v): self._kw["window"] = v; return self
        def learning_rate(self, v): self._kw["learning_rate"] = v; return self
        def min_learning_rate(self, v): self._kw["min_learning_rate"] = v; return self
        def negative_sample(self, v): self._kw["negative"] = int(v); return self
        def sampling(self, v): self._kw["sampling"] = v; return self
        def batch_size(self, v): self._kw["batch_size"] = v; return self

        def sequence_learning_algorithm(self, name: str):
            self._kw["dm"] = "dm" in name.lower() and "dbow" not in name.lower()
            return self

        def dm(self, flag: bool):
            self._kw["dm"] = flag
            return self

        def train_word_vectors(self, flag: bool):
            self._kw["train_word_vectors"] = flag
            return self

        def iterate(self, it: LabelAwareIterator):
            self._iter = it
            return self

        def tokenizer_factory(self, tf: TokenizerFactory):
            self._tok = tf
            return self

        def build(self) -> "ParagraphVectors":
            pv = ParagraphVectors(**self._kw)
            pv._doc_iter = self._iter
            pv._tokenizer = self._tok
            return pv

    @staticmethod
    def builder() -> "ParagraphVectors.Builder":
        return ParagraphVectors.Builder()

    def __init__(self, dm: bool = False, train_word_vectors: bool = True,
                 **kw):
        self.dm = dm
        # DL4J's ParagraphVectors trains element (word) vectors alongside
        # sequence vectors by default (trainElementsRepresentation=true);
        # in DBOW mode that means interleaved plain skip-gram pairs.
        self.train_word_vectors = train_word_vectors
        kw.setdefault("algorithm", "cbow" if dm else "skipgram")
        super().__init__(**kw)
        self._doc_iter: Optional[LabelAwareIterator] = None
        self._tokenizer: TokenizerFactory = DefaultTokenizerFactory()
        self._label_ids: List[int] = []

    # -- training ---------------------------------------------------------
    def fit(self) -> None:
        assert self._doc_iter is not None, "no corpus: call iterate() first"
        labels = self._doc_iter.labels
        docs_tokens = [self._tokenizer.create(s).get_tokens()
                       for s in self._doc_iter]
        self._special_tokens = labels
        self.build_vocab(iter(docs_tokens))
        self._label_ids = [self.vocab.index_of(l) for l in labels]
        # Encode per-doc (not via _encode_corpus) to keep label alignment
        # when a doc ends up empty after vocab pruning.
        corpus = []
        doc_labels = []
        for lbl, toks in zip(self._label_ids, docs_tokens):
            ids = [self.vocab.index_of(t) for t in toks]
            ids = np.asarray([i for i in ids if i >= 0], dtype=np.int32)
            if ids.size:
                corpus.append(ids)
                doc_labels.append(lbl)

        total = sum(len(s) for s in corpus) * self.epochs * self.iterations

        def stream(rng, keep):
            # Yields (corpus_words_consumed, *batch_payload) — the word
            # count drives the engine's LR schedule.
            for lbl, ids in zip(doc_labels, corpus):
                if self.dm:
                    wins = self._sentence_windows(ids, rng, keep)
                    if wins is None:
                        continue
                    c, ctx, cmask = wins
                    lbl_col = np.full((c.size, 1), lbl, dtype=np.int32)
                    ctx = np.concatenate([ctx, lbl_col], axis=1)
                    cmask = np.concatenate(
                        [cmask, np.ones((c.size, 1), np.float32)], axis=1)
                    yield ids.size, c, ctx, cmask
                else:
                    # PV-DBOW: label id predicts every (kept) word.
                    kept = ids[rng.random(ids.size) < keep[ids]] \
                        if self.sampling > 0 else ids
                    if kept.size == 0:
                        continue
                    centers = np.full(kept.size, lbl, dtype=np.int32)
                    if self.train_word_vectors:
                        pairs = self._sentence_pairs(ids, rng, keep)
                        if pairs is not None:
                            centers = np.concatenate([centers, pairs[0]])
                            kept = np.concatenate([kept, pairs[1]])
                    yield ids.size, centers, kept

        self._train_encoded(corpus, stream_factory=stream, total_words=total)

    # -- queries ----------------------------------------------------------
    def get_paragraph_vector(self, label: str) -> np.ndarray:
        return self.get_word_vector(label)

    def nearest_labels(self, vec_or_label, top_n: int = 5) -> List[str]:
        vec = (self.get_word_vector(vec_or_label)
               if isinstance(vec_or_label, str)
               else np.asarray(vec_or_label, np.float32))
        labels = set(self._label_ids)
        w = self.lookup_table.normalized()
        v = vec / max(np.linalg.norm(vec), 1e-12)
        sims = w @ v
        order = [i for i in np.argsort(-sims) if int(i) in labels]
        return [self.vocab.word_for(int(i)) for i in order[:top_n]]

    def infer_vector(self, text: str, steps: int = 50,
                     learning_rate: float = 0.025) -> np.ndarray:
        """Fit a vector for unseen text against FROZEN tables (reference:
        ParagraphVectors.inferVector → sg_cb inference-vector mode)."""
        import jax
        import jax.numpy as jnp

        from .vocab import unigram_table

        tokens = self._tokenizer.create(text).get_tokens()
        ids = np.asarray([i for i in (self.vocab.index_of(t) for t in tokens)
                          if i >= 0], dtype=np.int32)
        d = self.layer_size
        rng = np.random.default_rng(self.seed)
        vec = ((rng.random(d) - 0.5) / d).astype(np.float32)
        if ids.size == 0:
            return vec
        syn1 = jnp.asarray(self.lookup_table.syn1 if self.use_hs
                           else self.lookup_table.syn1neg)
        syn0 = jnp.asarray(self.lookup_table.syn0)
        cdf = unigram_table(self.vocab)
        V, K = len(self.vocab), max(self.negative, 1)

        if self.use_hs:
            from .vocab import huffman_arrays
            codes, points, mask = huffman_arrays(self.vocab)

            def loss_fn(v, tgt_ids):
                u = syn1[points[tgt_ids]]          # [N, L, D]
                m = jnp.asarray(mask[tgt_ids])
                labels = (1.0 - jnp.asarray(codes[tgt_ids],
                                            dtype=v.dtype)) * m
                logits = jnp.einsum("d,nld->nl", v, u)
                sig = jax.nn.sigmoid(logits)
                eps = 1e-7
                xe = -(labels * jnp.log(sig + eps)
                       + (1 - labels) * jnp.log(1 - sig + eps)) * m
                return xe.sum() / jnp.maximum(m.sum(), 1.0)

            grad = jax.jit(jax.grad(loss_fn))
            v = jnp.asarray(vec)
            for step in range(steps):
                lr = learning_rate * (1 - step / steps)
                v = v - lr * grad(v, jnp.asarray(ids))
            return np.asarray(v)

        def loss_fn(v, tgt, lab, ctxmean):
            u = syn1[tgt]                          # [N, K+1, D]
            h = v if not self.dm else (v + ctxmean) / 2.0
            logits = jnp.einsum("d,nkd->nk", h, u)
            sig = jax.nn.sigmoid(logits)
            eps = 1e-7
            xe = -(lab * jnp.log(sig + eps)
                   + (1 - lab) * jnp.log(1 - sig + eps))
            return xe.mean()

        grad = jax.jit(jax.grad(loss_fn))
        v = jnp.asarray(vec)
        ctxmean = jnp.mean(syn0[ids], axis=0)
        for step in range(steps):
            lr = learning_rate * (1 - step / steps)
            tgt, lab = self._neg_targets(ids, rng, cdf, V, K)
            v = v - lr * grad(v, jnp.asarray(tgt), jnp.asarray(lab), ctxmean)
        return np.asarray(v)
