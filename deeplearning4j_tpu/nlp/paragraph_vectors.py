"""ParagraphVectors (doc2vec): PV-DM and PV-DBOW.

Rebuild of the reference's ``models/paragraphvectors/ParagraphVectors`` with
its two sequence-learning algorithms (reference:
``models/embeddings/learning/impl/sequence/{DM,DBOW}.java``):

- **PV-DBOW** (``DBOW``): the document's label vector is the *input* row and
  every word of the document is a prediction target — exactly the skip-gram
  round with the label id as "center", so it reuses the fused ``skipgram``
  op unchanged.
- **PV-DM** (``DM``): the label vector joins the context-window average that
  predicts the center word — the CBOW round with one extra always-on context
  column carrying the label id.

Labels live in the SAME vocab/syn0 table as words (the reference adds them
as special VocabWords exempt from frequency pruning); ``infer_vector`` runs
gradient steps on a fresh row with frozen word/output tables, matching the
reference's inference-vector mode of the fused kernels (libnd4j sg_cb
``infVector`` path) — here it is simply ``jax.grad`` wrt the one vector.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..common import xprof
from .text import DefaultTokenizerFactory, LabelAwareIterator, TokenizerFactory
from .word2vec import SequenceVectors, _derive_windows, _pool_negs
from .vocab import subsample_keep_probs


class ParagraphVectors(SequenceVectors):
    class Builder:
        def __init__(self) -> None:
            self._kw = {}
            self._iter: Optional[LabelAwareIterator] = None
            self._tok: TokenizerFactory = DefaultTokenizerFactory()

        def min_word_frequency(self, v): self._kw["min_word_frequency"] = v; return self
        def iterations(self, v): self._kw["iterations"] = v; return self
        def epochs(self, v): self._kw["epochs"] = v; return self
        def layer_size(self, v): self._kw["layer_size"] = v; return self
        def seed(self, v): self._kw["seed"] = v; return self
        def window_size(self, v): self._kw["window"] = v; return self
        def learning_rate(self, v): self._kw["learning_rate"] = v; return self
        def min_learning_rate(self, v): self._kw["min_learning_rate"] = v; return self
        def negative_sample(self, v): self._kw["negative"] = int(v); return self
        def sampling(self, v): self._kw["sampling"] = v; return self
        def batch_size(self, v): self._kw["batch_size"] = v; return self

        def sequence_learning_algorithm(self, name: str):
            self._kw["dm"] = "dm" in name.lower() and "dbow" not in name.lower()
            return self

        def dm(self, flag: bool):
            self._kw["dm"] = flag
            return self

        def train_word_vectors(self, flag: bool):
            self._kw["train_word_vectors"] = flag
            return self

        def iterate(self, it: LabelAwareIterator):
            self._iter = it
            return self

        def tokenizer_factory(self, tf: TokenizerFactory):
            self._tok = tf
            return self

        def build(self) -> "ParagraphVectors":
            pv = ParagraphVectors(**self._kw)
            pv._doc_iter = self._iter
            pv._tokenizer = self._tok
            return pv

    @staticmethod
    def builder() -> "ParagraphVectors.Builder":
        return ParagraphVectors.Builder()

    def __init__(self, dm: bool = False, train_word_vectors: bool = True,
                 **kw):
        self.dm = dm
        # DL4J's ParagraphVectors trains element (word) vectors alongside
        # sequence vectors by default (trainElementsRepresentation=true);
        # in DBOW mode that means interleaved plain skip-gram pairs.
        self.train_word_vectors = train_word_vectors
        kw.setdefault("algorithm", "cbow" if dm else "skipgram")
        super().__init__(**kw)
        self._doc_iter: Optional[LabelAwareIterator] = None
        self._tokenizer: TokenizerFactory = DefaultTokenizerFactory()
        self._label_ids: List[int] = []

    # -- training ---------------------------------------------------------
    def fit(self) -> None:
        assert self._doc_iter is not None, "no corpus: call iterate() first"
        labels = self._doc_iter.labels
        docs_tokens = [self._tokenizer.create(s).get_tokens()
                       for s in self._doc_iter]
        self._special_tokens = labels
        self.build_vocab(iter(docs_tokens))
        self._label_ids = [self.vocab.index_of(l) for l in labels]
        # Encode per-doc (not via _encode_corpus) to keep label alignment
        # when a doc ends up empty after vocab pruning.
        corpus = []
        doc_labels = []
        for lbl, toks in zip(self._label_ids, docs_tokens):
            ids = [self.vocab.index_of(t) for t in toks]
            ids = np.asarray([i for i in ids if i >= 0], dtype=np.int32)
            if ids.size:
                corpus.append(ids)
                doc_labels.append(lbl)

        total = sum(len(s) for s in corpus) * self.epochs * self.iterations

        if getattr(self, "device_corpus", True) and self.mesh is None:
            # round-5: PV rides the same device-resident-corpus machinery
            # as skip-gram/CBOW (VERDICT r4 weak #1) — the host pair
            # pipeline below remains as the device_corpus=False fallback
            return self._train_windowed_pv(corpus, doc_labels, total)
        if self.mesh is not None:
            raise ValueError(
                "sharded tables (mesh=...) are implemented for the "
                "Word2Vec windowed paths only — ParagraphVectors would "
                "silently train unsharded")

        def stream(rng, keep):
            # Yields (corpus_words_consumed, *batch_payload) — the word
            # count drives the engine's LR schedule.
            for lbl, ids in zip(doc_labels, corpus):
                if self.dm:
                    wins = self._sentence_windows(ids, rng, keep)
                    if wins is None:
                        continue
                    c, ctx, cmask = wins
                    lbl_col = np.full((c.size, 1), lbl, dtype=np.int32)
                    ctx = np.concatenate([ctx, lbl_col], axis=1)
                    cmask = np.concatenate(
                        [cmask, np.ones((c.size, 1), np.float32)], axis=1)
                    yield ids.size, c, ctx, cmask
                else:
                    # PV-DBOW: label id predicts every (kept) word.
                    kept = ids[rng.random(ids.size) < keep[ids]] \
                        if self.sampling > 0 else ids
                    if kept.size == 0:
                        continue
                    centers = np.full(kept.size, lbl, dtype=np.int32)
                    if self.train_word_vectors:
                        pairs = self._sentence_pairs(ids, rng, keep)
                        if pairs is not None:
                            centers = np.concatenate([centers, pairs[0]])
                            kept = np.concatenate([kept, pairs[1]])
                    yield ids.size, centers, kept

        self._train_encoded(corpus, stream_factory=stream, total_words=total)

    # -- device-windowed path (round 5) -----------------------------------
    @property
    def _dbow_pairs(self) -> int:
        """Pairs per DBOW round — same stability cap as ``_round_pairs``
        (the scatter-add sums colliding row updates within a round; see
        word2vec.py). Collisions on the label row scale with doc LENGTH
        (consecutive positions share a label), exactly as they did in the
        host stream's per-doc batches, so the cap stays the vocab-derived
        one (plus the HS root-row cap — see word2vec._round_pairs)."""
        cap = min(self.batch_size, 8 * max(len(self.vocab), 1))
        if self.use_hs:
            cap = min(cap, self.HS_MAX_ROUND)
        return max(2, cap)

    def _make_dbow_window_block(self, hs_dev=None, ntable_dev=None):
        """Device DBOW block: every stream position is one training pair
        (center = the position's DOC LABEL row, target = the word) — the
        skip-gram round with the label as center (reference DBOW.java).
        Already dense (one pair per position, like the CBOW block), so a
        fixed-R ``lax.scan`` needs no compaction.

        Jitted ``(syn0, syn1, ids, labs, n_valid, negpool, p0, (lr0, lr1),
        key, blk_id) -> (syn0', syn1', mean_loss, n_pairs)``; ``labs`` is
        the per-position doc-label-id stream (uploaded once with the
        corpus)."""
        import functools

        import jax
        import jax.numpy as jnp
        from jax import lax

        from ..ops import embeddings as E

        is_hs = self.use_hs
        V, K, W = len(self.vocab), self.negative, self.window
        B = self._dbow_pairs
        R = self.MAX_BLOCK_ROUNDS
        S = B * R
        if is_hs:
            points_d, codes_d, mask_d = hs_dev
            self._win_negpool = jnp.zeros((8,), jnp.int32)
        else:
            lab = jnp.zeros((B, 1 + K), jnp.float32).at[:, 0].set(1.0)
            self._win_negpool = self._build_negpool(ntable_dev, B * K)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def block(syn0, syn1, ids, labs, pos_map, n_valid, negpool, p0,
                  lr01, key, blk_id):
            key = jax.random.fold_in(key, blk_id)
            # SHUFFLED pair order (``pos_map``: per-epoch permutation with
            # valid positions first): a round of B CONSECUTIVE positions
            # would sum ~doc-length colliding updates into each label row,
            # and with syn1=0 init that amplifies the shared mean
            # direction until every doc vector is collinear (measured:
            # sims 0.99 across clusters). The reference avoids this by
            # applying pairs serially; spreading a round across the corpus
            # is the batched equivalent. DOCUMENTED divergence from the
            # reference's corpus-order stream.
            pos = lax.dynamic_slice(pos_map, (p0,), (S,))
            idw = ids[pos + W].astype(jnp.int32)
            labw = labs[pos + W].astype(jnp.int32)
            lr0, lr1 = lr01

            def body(carry, r):
                s0, s1 = carry
                sl = r * B
                x = lax.dynamic_slice(idw, (sl,), (B,))
                c = lax.dynamic_slice(labw, (sl,), (B,))
                pm = ((p0 + sl + lax.broadcasted_iota(jnp.int32, (B,), 0))
                      < n_valid).astype(jnp.float32)
                lr = lr0 + (lr1 - lr0) * r.astype(jnp.float32) / R
                if is_hs:
                    s0, s1, loss = E.skipgram_hs(
                        s0, s1, c, points_d[x], codes_d[x], mask_d[x],
                        lr, pm, dense=False)
                else:
                    negs = _pool_negs(negpool, blk_id, r, B, K, V, x)
                    tgt = jnp.concatenate([x[:, None], negs], axis=1)
                    s0, s1, loss = E.skipgram(s0, s1, c, tgt, lab, lr, pm,
                                              dense=False)
                return (s0, s1), (loss, pm.sum())

            (syn0, syn1), (losses, ns) = lax.scan(
                body, (syn0, syn1), jnp.arange(R, dtype=jnp.int32))
            return (syn0, syn1,
                    (losses * ns).sum() / jnp.maximum(ns.sum(), 1.0),
                    ns.sum())

        return xprof.register_jit("nlp/pv_dbow_block", block,
                                  donate=(0, 1))

    def _make_dm_window_block(self, hs_dev=None, ntable_dev=None):
        """Device PV-DM block: the CBOW windowed block with the doc-label
        vector joined to the context mean as one always-on extra context
        column (reference DM.java). Context windows come from the shared
        ``_derive_windows``; an empty reduced window still trains (the
        mean is the label vector alone — host-path semantics)."""
        import functools

        import jax
        import jax.numpy as jnp
        from jax import lax

        from ..ops import embeddings as E

        is_hs = self.use_hs
        V, K, W = len(self.vocab), self.negative, self.window
        B_C = self._cbow_centers
        R = self.MAX_BLOCK_ROUNDS
        S = B_C * R
        if is_hs:
            points_d, codes_d, mask_d = hs_dev
            self._win_negpool = jnp.zeros((8,), jnp.int32)
        else:
            lab = jnp.zeros((B_C, 1 + K), jnp.float32).at[:, 0].set(1.0)
            self._win_negpool = self._build_negpool(ntable_dev, B_C * K)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def block(syn0, syn1, ids, sent, labs, n_valid, negpool, p0, lr01,
                  key, blk_id):
            key = jax.random.fold_in(key, blk_id)
            c_ids, ctx_all, valid, live = _derive_windows(
                ids, sent, n_valid, p0, S, W, key)
            labw = lax.dynamic_slice(labs, (p0 + W,), (S,)).astype(jnp.int32)
            cm_all = valid.astype(jnp.float32)
            lr0, lr1 = lr01
            ones = jnp.ones((B_C, 1), jnp.float32)

            def body(carry, r):
                s0, s1 = carry
                sl = r * B_C
                c = lax.dynamic_slice(c_ids, (sl,), (B_C,))
                cx = lax.dynamic_slice(ctx_all, (sl, jnp.int32(0)),
                                       (B_C, 2 * W))
                cm = lax.dynamic_slice(cm_all, (sl, jnp.int32(0)),
                                       (B_C, 2 * W))
                lb = lax.dynamic_slice(labw, (sl,), (B_C,))
                cx = jnp.concatenate([cx, lb[:, None]], axis=1)
                cm = jnp.concatenate([cm, ones], axis=1)
                lv = lax.dynamic_slice(live, (sl,), (B_C,))
                pm = lv.astype(jnp.float32)   # label col is always on
                lr = lr0 + (lr1 - lr0) * r.astype(jnp.float32) / R
                if is_hs:
                    s0, s1, loss = E.cbow_hs(
                        s0, s1, cx, cm, points_d[c], codes_d[c], mask_d[c],
                        lr, pm, dense=False)
                else:
                    negs = _pool_negs(negpool, blk_id, r, B_C, K, V, c)
                    tgt = jnp.concatenate([c[:, None], negs], axis=1)
                    s0, s1, loss = E.cbow(s0, s1, cx, cm, tgt, lab, lr,
                                          pm, dense=False)
                return (s0, s1), (loss, pm.sum())

            (syn0, syn1), (losses, ns) = lax.scan(
                body, (syn0, syn1), jnp.arange(R, dtype=jnp.int32))
            return (syn0, syn1,
                    (losses * ns).sum() / jnp.maximum(ns.sum(), 1.0),
                    ns.sum())

        return xprof.register_jit("nlp/pv_dm_block", block, donate=(0, 1))

    def _pos_map_fn(self, pos_len: int):
        """Per-epoch jitted builder of the DBOW pair-order shuffle: a
        [pos_len] permutation with the n_valid live stream positions
        first, in random order (see the block docstring for why)."""
        cache = getattr(self, "_pos_map_jit", None)
        if cache is None:
            cache = self._pos_map_jit = {}
        if pos_len not in cache:
            import jax
            import jax.numpy as jnp
            from jax import lax

            @jax.jit
            def fn(n_valid, key):
                iota = lax.broadcasted_iota(jnp.int32, (pos_len,), 0)
                u = jax.random.uniform(key, (pos_len,))
                rank = jnp.where(iota < n_valid, u,
                                 2.0 + iota.astype(jnp.float32))
                return jnp.argsort(rank).astype(jnp.int32)

            cache[pos_len] = xprof.register_jit("nlp/pv_pos_map", fn)
        return cache[pos_len]

    def _subsample3_fn(self):
        """Device subsampling that compacts the (ids, sent, labs) triple
        with one shared slot map (the word2vec ``_subsample_fn`` with the
        label stream riding along)."""
        cached = getattr(self, "_subsample3_jit", None)
        if cached is not None and cached[0] == self.window:
            return cached[1]
        import jax
        import jax.numpy as jnp
        from jax import lax

        W = self.window

        @jax.jit
        def fn(ids, sent, labs, keep_dev, n_full, key):
            N = ids.shape[0]
            iota = lax.broadcasted_iota(jnp.int32, (N,), 0)
            u = jax.random.uniform(key, (N,))
            vf = ((u < keep_dev[ids.astype(jnp.int32)])
                  & (iota >= W) & (iota < W + n_full))
            dest = jnp.cumsum(vf.astype(jnp.int32)) - 1
            slot = jnp.where(vf, dest + W, N)
            ids_sub = jnp.zeros((N,), ids.dtype).at[slot].set(
                ids, mode="drop")
            sent_sub = jnp.full(
                # graftlint: disable=host-sync-in-step -- trace-time
                # constant: iinfo folds into the trace, no runtime sync
                (N,), np.iinfo(np.uint16).max,
                sent.dtype).at[slot].set(sent, mode="drop")
            labs_sub = jnp.zeros((N,), labs.dtype).at[slot].set(
                labs, mode="drop")
            return ids_sub, sent_sub, labs_sub, dest[-1] + 1

        fn = xprof.register_jit("nlp/pv_subsample", fn)
        self._subsample3_jit = (W, fn)
        return fn

    def _train_windowed_pv(self, corpus: List[np.ndarray],
                           doc_labels: List[int], total_words: int) -> None:
        """Device-resident-corpus fit for PV-DM / PV-DBOW: the word2vec
        ``_train_windowed`` loop with a per-position doc-label stream.
        DBOW with ``train_word_vectors`` (the reference default) runs the
        plain skip-gram windowed block over the same device corpus as a
        second pass each epoch — the reference interleaves word and doc
        pairs per document; at LR-schedule granularity the two orders are
        statistically equivalent (both passes see the epoch's LR ramp)."""
        import jax
        import jax.numpy as jnp

        keep = subsample_keep_probs(self.vocab, self.sampling)
        raw_words = sum(len(s) for s in corpus)
        if raw_words == 0:
            return

        is_dm = self.dm
        if is_dm:
            pv_block = self._block_for("dmwin", self._make_dm_window_block,
                                       self.window, self._cbow_centers)
            pv_span = self._cbow_centers * self.MAX_BLOCK_ROUNDS
        else:
            pv_block = self._block_for("dbowwin",
                                       self._make_dbow_window_block,
                                       self._dbow_pairs)
            pv_span = self._dbow_pairs * self.MAX_BLOCK_ROUNDS
        word_pass = (not is_dm) and self.train_word_vectors
        if word_pass:
            sg_block = self._block_for("win", self._make_window_block,
                                       self.window, self._window_centers,
                                       None)
            sg_span = self._window_span
        else:
            sg_block, sg_span = None, pv_span

        flat = np.concatenate(corpus).astype(np.int32)
        lens = np.array([c.size for c in corpus], dtype=np.int64)
        assert self.window < 65535
        sent_full = (np.repeat(np.arange(len(corpus), dtype=np.int64), lens)
                     % 65535).astype(np.uint16)
        labs_full = np.repeat(np.asarray(doc_labels, np.int32), lens)
        idx_dt = (np.uint16 if len(self.vocab) <= (1 << 16) else np.int32)

        base_key = jax.random.PRNGKey(self.seed)
        tdt = (jnp.bfloat16 if getattr(self, "table_dtype", "float32")
               == "bfloat16" else jnp.float32)
        syn1_host = (self.lookup_table.syn1 if self.use_hs
                     else self.lookup_table.syn1neg)
        syn0 = jnp.asarray(self.lookup_table.syn0, tdt)
        syn1 = jnp.asarray(syn1_host, tdt)

        W = self.window
        npad = -(-max(flat.size, 1) // self.CORPUS_BUCKET) \
            * self.CORPUS_BUCKET
        span_max = max(pv_span, sg_span)
        buf_len = npad + span_max + 2 * W
        ckey = (flat.size, hash(flat.tobytes()), hash(labs_full.tobytes()),
                buf_len, str(idx_dt))
        cached = getattr(self, "_pv_corpus_dev_cache", None)
        if cached is not None and cached[0] == ckey:
            ids_full, sent_full_dev, labs_dev = cached[1]
        else:
            ids_np = np.zeros(buf_len, idx_dt)
            ids_np[W:W + flat.size] = flat.astype(idx_dt)
            sent_np = np.full(buf_len, np.iinfo(np.uint16).max, np.uint16)
            sent_np[W:W + flat.size] = sent_full
            labs_np = np.zeros(buf_len, np.int32)
            labs_np[W:W + flat.size] = labs_full
            ids_full = jax.device_put(ids_np)
            sent_full_dev = jax.device_put(sent_np)
            labs_dev = jax.device_put(labs_np)
            self._pv_corpus_dev_cache = (ckey,
                                         (ids_full, sent_full_dev, labs_dev))
        n_raw = flat.size

        if self.sampling > 0:
            keep_dev = jnp.asarray(keep.astype(np.float32))
            sub3 = self._subsample3_fn()
            ksub_base = jax.random.fold_in(base_key, (1 << 31) - 1)
            kf = keep[flat]
            n_exp = float(kf.sum())
            n_loop = min(n_raw, int(n_exp + 6.0 * np.sqrt(
                max(float((kf * (1.0 - kf)).sum()), 1.0)) + 1))
        else:
            n_exp = float(n_raw)
            n_loop = n_raw

        def lr_at(frac: float) -> np.float32:
            return np.float32(max(
                self.learning_rate * (1.0 - min(frac, 1.0)),
                self.min_learning_rate))

        losses, pair_counts = [], []
        n_blocks = 0
        words_seen = 0
        t0 = time.perf_counter()
        kshuf_base = jax.random.fold_in(base_key, 0x7EAF)
        pos_fn = None if is_dm else self._pos_map_fn(npad + pv_span)
        for _epoch in range(self.epochs):
            if self.sampling > 0:
                ids_dev, sent_dev, labs_sub, n_valid = sub3(
                    ids_full, sent_full_dev, labs_dev, keep_dev,
                    np.int32(n_raw), jax.random.fold_in(ksub_base, _epoch))
            else:
                ids_dev, sent_dev, labs_sub = (ids_full, sent_full_dev,
                                               labs_dev)
                n_valid = np.int32(n_raw)
            pos_map = (None if is_dm else
                       pos_fn(n_valid, jax.random.fold_in(kshuf_base,
                                                          _epoch)))
            for _it in range(self.iterations):
                it_base = words_seen

                def _lr01(p0, span):
                    lr0 = lr_at((it_base + p0 / max(n_exp, 1.0) * raw_words)
                                / max(total_words, 1))
                    lr1 = lr_at((it_base
                                 + min(p0 + span, n_loop) / max(n_exp, 1.0)
                                 * raw_words) / max(total_words, 1))
                    return lr0, lr1

                if word_pass:
                    for p0 in range(0, n_loop, sg_span):
                        syn0, syn1, loss, np_ = sg_block(
                            syn0, syn1, ids_dev, sent_dev, n_valid,
                            self._win_negpool, np.int32(p0),
                            _lr01(p0, sg_span), base_key,
                            np.int32(n_blocks))
                        n_blocks += 1
                        losses.append(loss)
                        pair_counts.append(np_)
                for p0 in range(0, n_loop, pv_span):
                    if is_dm:
                        syn0, syn1, loss, np_ = pv_block(
                            syn0, syn1, ids_dev, sent_dev, labs_sub,
                            n_valid, self._win_negpool, np.int32(p0),
                            _lr01(p0, pv_span), base_key,
                            np.int32(n_blocks))
                    else:
                        syn0, syn1, loss, np_ = pv_block(
                            syn0, syn1, ids_dev, labs_sub, pos_map,
                            n_valid, self._win_negpool, np.int32(p0),
                            _lr01(p0, pv_span), base_key,
                            np.int32(n_blocks))
                    n_blocks += 1
                    losses.append(loss)
                    pair_counts.append(np_)
                words_seen += raw_words
        last = (np.asarray(jnp.stack(losses[-50:])) if losses
                else np.zeros(1, np.float32))
        pairs_seen = (float(np.asarray(jnp.stack(pair_counts)).sum())
                      if pair_counts else 0.0)
        dt = time.perf_counter() - t0
        self.words_per_sec = words_seen / max(dt, 1e-9)
        self.pairs_per_sec = pairs_seen / max(dt, 1e-9)
        self.last_loss = float(last.mean()) if losses else 0.0
        self.lookup_table.syn0 = np.asarray(syn0.astype(jnp.float32))
        if self.use_hs:
            self.lookup_table.syn1 = np.asarray(syn1.astype(jnp.float32))
        else:
            self.lookup_table.syn1neg = np.asarray(syn1.astype(jnp.float32))

    # -- queries ----------------------------------------------------------
    def get_paragraph_vector(self, label: str) -> np.ndarray:
        return self.get_word_vector(label)

    def nearest_labels(self, vec_or_label, top_n: int = 5) -> List[str]:
        vec = (self.get_word_vector(vec_or_label)
               if isinstance(vec_or_label, str)
               else np.asarray(vec_or_label, np.float32))
        labels = set(self._label_ids)
        w = self.lookup_table.normalized()
        v = vec / max(np.linalg.norm(vec), 1e-12)
        sims = w @ v
        order = [i for i in np.argsort(-sims) if int(i) in labels]
        return [self.vocab.word_for(int(i)) for i in order[:top_n]]

    def infer_vector(self, text: str, steps: int = 50,
                     learning_rate: float = 0.025) -> np.ndarray:
        """Fit a vector for unseen text against FROZEN tables (reference:
        ParagraphVectors.inferVector → sg_cb inference-vector mode)."""
        import jax
        import jax.numpy as jnp

        from .vocab import unigram_table

        tokens = self._tokenizer.create(text).get_tokens()
        ids = np.asarray([i for i in (self.vocab.index_of(t) for t in tokens)
                          if i >= 0], dtype=np.int32)
        d = self.layer_size
        rng = np.random.default_rng(self.seed)
        vec = ((rng.random(d) - 0.5) / d).astype(np.float32)
        if ids.size == 0:
            return vec
        syn1 = jnp.asarray(self.lookup_table.syn1 if self.use_hs
                           else self.lookup_table.syn1neg)
        syn0 = jnp.asarray(self.lookup_table.syn0)
        cdf = unigram_table(self.vocab)
        V, K = len(self.vocab), max(self.negative, 1)

        if self.use_hs:
            from .vocab import huffman_arrays
            codes, points, mask = huffman_arrays(self.vocab)

            def loss_fn(v, tgt_ids):
                u = syn1[points[tgt_ids]]          # [N, L, D]
                m = jnp.asarray(mask[tgt_ids])
                labels = (1.0 - jnp.asarray(codes[tgt_ids],
                                            dtype=v.dtype)) * m
                logits = jnp.einsum("d,nld->nl", v, u)
                sig = jax.nn.sigmoid(logits)
                eps = 1e-7
                xe = -(labels * jnp.log(sig + eps)
                       + (1 - labels) * jnp.log(1 - sig + eps)) * m
                return xe.sum() / jnp.maximum(m.sum(), 1.0)

            # graftlint: disable=executable-census -- fresh jit per
            # infer_vector call over a per-call closure; the census
            # tracks long-lived executables, not per-call wrappers
            grad = jax.jit(jax.grad(loss_fn))
            v = jnp.asarray(vec)
            for step in range(steps):
                lr = learning_rate * (1 - step / steps)
                v = v - lr * grad(v, jnp.asarray(ids))
            return np.asarray(v)

        def loss_fn(v, tgt, lab, ctxmean):
            u = syn1[tgt]                          # [N, K+1, D]
            h = v if not self.dm else (v + ctxmean) / 2.0
            logits = jnp.einsum("d,nkd->nk", h, u)
            sig = jax.nn.sigmoid(logits)
            eps = 1e-7
            xe = -(lab * jnp.log(sig + eps)
                   + (1 - lab) * jnp.log(1 - sig + eps))
            return xe.mean()

        # graftlint: disable=executable-census -- fresh jit per
        # infer_vector call over a per-call closure (see above)
        grad = jax.jit(jax.grad(loss_fn))
        v = jnp.asarray(vec)
        ctxmean = jnp.mean(syn0[ids], axis=0)
        for step in range(steps):
            lr = learning_rate * (1 - step / steps)
            tgt, lab = self._neg_targets(ids, rng, cdf, V, K)
            v = v - lr * grad(v, jnp.asarray(tgt), jnp.asarray(lab), ctxmean)
        return np.asarray(v)
