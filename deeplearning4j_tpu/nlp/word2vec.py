"""SequenceVectors engine + Word2Vec front (reference: deeplearning4j-nlp
``models/sequencevectors/SequenceVectors`` and ``models/word2vec/Word2Vec``).

Architecture (vs the reference, SURVEY §3.6): the reference trains with N
Java worker threads each dispatching one fused ``SkipGramRound`` JNI kernel
per (center, context) pair. The TPU rebuild keeps the same statistical
procedure — frequency-pruned vocab, frequent-word subsampling, per-position
reduced window, unigram^0.75 negative sampling or Huffman hierarchical
softmax, linear LR decay — but restructures the hot loop hardware-first
(BASELINE.md "Word2Vec audit" records the measurements behind each choice):

- DEFAULT paths — skip-gram AND CBOW (``_train_windowed``, round 4): the
  corpus is uploaded ONCE and lives on device; every dispatch derives its
  windows there (shifted slices), draws negatives from a pre-drawn pool,
  and scatter-updates only the touched table rows. Skip-gram additionally
  compacts its (center, context) pairs densely before training.
- custom streams (ParagraphVectors) and ``device_corpus=False`` use the
  host pair pipeline: vectorized/native pair generation buffered into
  fixed-size uint16 column blocks, staged to device from a producer
  thread (``common/background.prefetch_iter``);
- both paths run ONE jitted ``lax.scan`` block per dispatch
  (``ops/embeddings.py`` fused rounds, tables donated) and compile exactly
  ONE block shape per fit;
- the reference's ``workers`` thread knob is accepted and recorded but
  parallelism comes from batching on the MXU, not host threads.

``iterations`` follows the reference semantics (each sentence's pairs are
trained `iterations` times per epoch); ``epochs`` is the corpus pass count.
"""

from __future__ import annotations

import time
from typing import Callable, Iterable, List, Optional, Sequence

import numpy as np

from ..common import xprof
from .lookup_table import InMemoryLookupTable
from .text import (CollectionSentenceIterator, DefaultTokenizerFactory,
                   SentenceIterator, TokenizerFactory)
from .vocab import (VocabCache, VocabConstructor, build_huffman,
                    huffman_arrays, subsample_keep_probs, unigram_int_table,
                    unigram_table)


class WordVectors:
    """Query surface shared by Word2Vec/ParagraphVectors and models loaded
    from serialized vectors (reference: WordVectors interface —
    getWordVector / similarity / wordsNearest / accuracy)."""

    def __init__(self, vocab: VocabCache, table: InMemoryLookupTable):
        self.vocab = vocab
        self.lookup_table = table

    # -- basic lookups ----------------------------------------------------
    def has_word(self, word: str) -> bool:
        return word in self.vocab

    def get_word_vector(self, word: str) -> np.ndarray:
        idx = self.vocab.index_of(word)
        if idx < 0:
            raise KeyError(f"word not in vocab: {word!r}")
        return self.lookup_table.vector(idx)

    def get_word_vector_matrix(self) -> np.ndarray:
        return np.asarray(self.lookup_table.syn0)

    # -- similarity / nearest --------------------------------------------
    def similarity(self, w1: str, w2: str) -> float:
        a, b = self.get_word_vector(w1), self.get_word_vector(w2)
        na, nb = np.linalg.norm(a), np.linalg.norm(b)
        if na == 0 or nb == 0:
            return 0.0
        return float(a @ b / (na * nb))

    def words_nearest(self, word_or_vec, top_n: int = 10) -> List[str]:
        if isinstance(word_or_vec, str):
            vec = self.get_word_vector(word_or_vec)
            exclude = {self.vocab.index_of(word_or_vec)}
        else:
            vec = np.asarray(word_or_vec, dtype=np.float32)
            exclude = set()
        w = self.lookup_table.normalized()
        v = vec / max(np.linalg.norm(vec), 1e-12)
        sims = w @ v
        order = np.argsort(-sims)
        out = []
        for idx in order:
            if int(idx) in exclude:
                continue
            out.append(self.vocab.word_for(int(idx)))
            if len(out) == top_n:
                break
        return out

    def accuracy(self, questions: Sequence[Sequence[str]]) -> float:
        """Analogy accuracy: each question is (a, b, c, expected) testing
        b - a + c ≈ expected (reference: WordVectors.accuracy over the
        Google questions-words format)."""
        correct = total = 0
        for a, b, c, expected in questions:
            if not all(self.has_word(w) for w in (a, b, c, expected)):
                continue
            total += 1
            vec = (self.get_word_vector(b) - self.get_word_vector(a)
                   + self.get_word_vector(c))
            nearest = self.words_nearest(vec, top_n=4)
            preds = [w for w in nearest if w not in (a, b, c)]
            if preds and preds[0] == expected:
                correct += 1
        return correct / total if total else 0.0


class SequenceVectors(WordVectors):
    """The distributed-representation training engine; Word2Vec and
    ParagraphVectors are thin configuration fronts over it (mirrors the
    reference's SequenceVectors inheritance)."""

    def __init__(self, *, layer_size: int = 100, window: int = 5,
                 learning_rate: float = 0.025, min_learning_rate: float = 1e-4,
                 negative: int = 5, use_hierarchic_softmax: bool = False,
                 sampling: float = 0.0, min_word_frequency: int = 5,
                 iterations: int = 1, epochs: int = 1, batch_size: int = 512,
                 seed: int = 42, algorithm: str = "skipgram",
                 workers: int = 1, table_dtype: str = "float32",
                 mesh=None, table_sharding_axis: str = "model",
                 special_tokens: Sequence[str] = ()):
        if use_hierarchic_softmax:
            # DOCUMENTED DIVERGENCE: the reference can train HS and negative
            # sampling simultaneously; this engine trains exactly one output
            # path per fit. Silent dropping would serialize an untrained
            # syn1neg as if it were state — refuse instead.
            if negative == 5:      # the constructor default
                negative = 0
            elif negative > 0:
                raise ValueError(
                    "combined hierarchical-softmax + negative-sampling "
                    "training is not implemented; set negative=0 with "
                    "use_hierarchic_softmax=True (or disable HS)")
        elif negative <= 0:
            raise ValueError("need negative sampling (negative>0) or "
                             "use_hierarchic_softmax=True")
        self.layer_size = layer_size
        self.window = window
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.negative = negative
        self.use_hs = use_hierarchic_softmax
        self.sampling = sampling
        self.min_word_frequency = min_word_frequency
        self.iterations = iterations
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.algorithm = algorithm.lower()
        if self.algorithm not in ("skipgram", "cbow"):
            raise ValueError(f"unknown algorithm {algorithm!r}")
        # Accepted for reference config parity; batching on the MXU replaces
        # host worker threads (see module docstring).
        self.workers = workers
        # "bfloat16" halves table gather/scatter HBM traffic on the
        # device-windowed path; stored vectors are cast back to float32
        # after the fit. Default stays float32 (bit-identical convergence
        # with the reference-shaped procedure).
        if table_dtype not in ("float32", "bfloat16"):
            raise ValueError(f"table_dtype must be float32|bfloat16, "
                             f"got {table_dtype!r}")
        self.table_dtype = table_dtype
        # Row-sharded syn0/syn1 over a mesh axis — the reference's
        # VoidParameterServer sharded exactly this workload (SURVEY §2.4
        # row 4); here the device-windowed block runs under shard_map with
        # psum-assembled row lookups (ops/embeddings.py sharded_skipgram).
        if mesh is not None and self.use_hs:
            raise ValueError("sharded tables support negative sampling "
                             "only (use_hierarchic_softmax=False)")
        self.mesh = mesh
        self.table_sharding_axis = table_sharding_axis
        self._special_tokens = list(special_tokens)
        self.words_per_sec: float = 0.0
        super().__init__(VocabCache(), InMemoryLookupTable(0, layer_size))

    # -- corpus encoding --------------------------------------------------
    def _encode_corpus(self, token_seqs: Iterable[List[str]]) -> List[np.ndarray]:
        enc = []
        for tokens in token_seqs:
            ids = [self.vocab.index_of(t) for t in tokens]
            ids = np.asarray([i for i in ids if i >= 0], dtype=np.int32)
            if ids.size:
                enc.append(ids)
        return enc

    def build_vocab(self, token_seqs: Iterable[List[str]]) -> None:
        self.vocab = VocabConstructor(
            self.min_word_frequency,
            special_tokens=self._special_tokens).build(token_seqs)
        if self.use_hs:
            build_huffman(self.vocab)
        self.lookup_table = InMemoryLookupTable(
            len(self.vocab), self.layer_size, seed=self.seed)
        self.lookup_table.reset_weights(self.use_hs, self.negative > 0)

    # -- pair generation (vectorized, host) -------------------------------
    def _sentence_pairs(self, ids: np.ndarray, rng: np.random.Generator,
                        keep: np.ndarray):
        """(centers, contexts) int32 arrays for one sentence: frequent-word
        subsampling then per-position reduced window b ~ U[1, window]."""
        if self.sampling > 0:
            ids = ids[rng.random(ids.size) < keep[ids]]
        n = ids.size
        if n < 2:
            return None
        W = self.window
        b = rng.integers(1, W + 1, size=n)
        offs = np.concatenate([np.arange(-W, 0), np.arange(1, W + 1)])
        pos = np.arange(n)[:, None] + offs[None, :]            # [n, 2W]
        valid = ((np.abs(offs)[None, :] <= b[:, None])
                 & (pos >= 0) & (pos < n))
        centers = np.broadcast_to(ids[:, None], valid.shape)[valid]
        contexts = ids[np.clip(pos, 0, n - 1)][valid]
        return centers, contexts

    def _sentence_windows(self, ids: np.ndarray, rng: np.random.Generator,
                          keep: np.ndarray):
        """CBOW grouping: (centers [n], contexts [n, 2W], ctx_mask [n, 2W])
        — the full reduced window per center position."""
        if self.sampling > 0:
            ids = ids[rng.random(ids.size) < keep[ids]]
        n = ids.size
        if n < 2:
            return None
        W = self.window
        b = rng.integers(1, W + 1, size=n)
        offs = np.concatenate([np.arange(-W, 0), np.arange(1, W + 1)])
        pos = np.arange(n)[:, None] + offs[None, :]
        valid = ((np.abs(offs)[None, :] <= b[:, None])
                 & (pos >= 0) & (pos < n))
        contexts = ids[np.clip(pos, 0, n - 1)] * valid
        return ids, contexts.astype(np.int32), valid.astype(np.float32)

    # -- device step ------------------------------------------------------
    # Max training rounds fused into one device dispatch. Through the TPU
    # relay a dispatch costs tens of ms regardless of payload, so the hot
    # loop runs a lax.scan over up to this many rounds per call (measured
    # ~3× throughput vs one-round-per-dispatch at B=8192).
    MAX_BLOCK_ROUNDS = 64
    # A whole fit compiles exactly ONE block shape: mid-fit flushes emit
    # only full blocks (remainders carry forward), and the single final
    # tail is mask-padded up to a full block (≤63 no-op rounds ≈ 75 ms of
    # device time). Round-3 finding: the earlier pow2 tail splitting
    # compiled up to 7 shapes at ~4–15 s EACH on TPU — compilation, not
    # compute, dominated the entire fit.

    # Corpus device buffers are padded to this multiple so distinct corpus
    # sizes reuse a handful of compiled shapes.
    CORPUS_BUCKET = 1 << 16
    # Pre-drawn negative-sample pool entries (device int32, ~32 MB): the
    # NS path consumes pool windows at prime-stride offsets instead of
    # gathering the unigram table per candidate (see _make_window_block).
    NEG_POOL_SIZE = 1 << 23
    # Hierarchical-softmax round-size cap: every pair's path hits the
    # Huffman root, so summed-scatter collisions per round == round size
    # (see _round_pairs).
    HS_MAX_ROUND = 128

    @property
    def _window_centers(self) -> int:
        """Centers per device-windowed round, sized so one round trains
        ~batch_size (center, context) slots. batch_size stays the
        stability knob it is on the host path: per-round updates into one
        table row scale with examples-per-round, and a tiny vocab with a
        huge round diverges (observed: NaN at 10k slots/round over a
        12-word vocab)."""
        return max(1, self.batch_size // (2 * self.window))

    @property
    def _round_pairs(self) -> int:
        """Dense training pairs per round. Capped by vocab size: the
        scatter-add SUMS colliding row updates within a round (the
        reference applies pairs serially, each against the current row),
        so a tiny vocab with a big round compounds updates and diverges —
        measured on a 16-word vocab: ~100 expected collisions per syn1 row
        per round trains cleanly (the round-3 masked path's stable
        operating point), ~190 explodes to 1e15 norms, ~380 NaNs. 8·V
        keeps expected collisions (B·(1+K)/V ≈ 48) comfortably inside the
        stable regime while leaving any vocab ≥ ~1k at the full
        batch-size-derived round."""
        B = self._window_centers * 2 * self.window
        cap = min(B, 8 * max(len(self.vocab), 1))
        floor = max(2 * self.window, 2)
        if self.use_hs:
            # HS concentrates EVERY pair's update on the Huffman ROOT row
            # (and nearly every pair on the top tree nodes), so collisions
            # per round equal the round size itself — far past the ~190
            # summed-update stability boundary at the NS cap. Measured on
            # the 4M-word bench corpus: B=8190 NaNs, B<=HS_MAX_ROUND
            # trains cleanly (round 5). The cap must also beat the 2W
            # floor, or window>=65 would reintroduce the NaN.
            return min(max(floor, cap), self.HS_MAX_ROUND)
        return max(floor, cap)

    @property
    def _window_span(self) -> int:
        """Corpus positions consumed per packed dispatch, sized so the
        EXPECTED pair count (≤ (W+1) per position) fills MAX_BLOCK_ROUNDS
        dense rounds of B slots."""
        return max(1, (self._round_pairs * self.MAX_BLOCK_ROUNDS)
                   // (self.window + 1))

    def _subsample_fn(self):
        """Jitted device-side frequent-word subsampling + stream
        compaction: ``(ids, sent, keep, n_full, key) -> (ids', sent',
        count)``. Same cumsum→scatter compaction as the pair packer;
        padding slots get the uint16 sentinel sentence id so window
        boundary checks fail there."""
        # keyed on window: W is baked into the closure (stream offset)
        fn = None
        cached = getattr(self, "_subsample_jit", None)
        if cached is not None and cached[0] == self.window:
            fn = cached[1]
        if fn is None:
            import jax
            import jax.numpy as jnp
            from jax import lax

            W = self.window

            @jax.jit
            def fn(ids, sent, keep_dev, n_full, key):
                N = ids.shape[0]
                iota = lax.broadcasted_iota(jnp.int32, (N,), 0)
                u = jax.random.uniform(key, (N,))
                # the stream occupies buffer slots [W, W+n_full) (front
                # pad, see _train_windowed); the compacted stream is
                # rewritten at the same W offset
                vf = ((u < keep_dev[ids.astype(jnp.int32)])
                      & (iota >= W) & (iota < W + n_full))
                dest = jnp.cumsum(vf.astype(jnp.int32)) - 1
                slot = jnp.where(vf, dest + W, N)
                ids_sub = jnp.zeros((N,), ids.dtype).at[slot].set(
                    ids, mode="drop")
                sent_sub = jnp.full(
                    # graftlint: disable=host-sync-in-step -- trace-time
                    # constant: iinfo folds into the trace, no runtime sync
                    (N,), np.iinfo(np.uint16).max,
                    sent.dtype).at[slot].set(sent, mode="drop")
                return ids_sub, sent_sub, dest[-1] + 1

            fn = xprof.register_jit("nlp/w2v_subsample", fn)
            self._subsample_jit = (self.window, fn)
        return fn

    def _make_block(self, hs_dev=None, ntable_dev=None):
        """Jitted (syn0, syn1, cols, key) -> (syn0', syn1', mean_loss)
        running a ``lax.scan`` of fused rounds.

        The column format is sized for the measured transport, not for
        convenience (round-3 relay audit, BASELINE.md: host→device moves
        5–10 MB/s, so bytes-on-the-wire IS the throughput):

        - word indices travel as uint16 whenever the vocab fits (cast to
          int32 on device);
        - the per-pair float mask became a per-round valid-pair COUNT,
          expanded to a mask on device with one iota compare;
        - NS negatives never travel at all: the whole block's draws happen
          on device in ONE bulk gather from a 2^20-slot unigram^0.75 int
          table (``unigram_int_table`` — the reference's own table design)
          before the scan. Bulk ``random_bits`` + gather replaced the
          per-round searchsorted that was 65% of round-2's device profile.
        - HS configs gather Huffman paths from device-resident tables
          (``hs_dev``) by word index, as before.

        RNG divergence from the reference's host-side PCG sampling is
        DOCUMENTED (SURVEY declares statistical, not bitwise, parity).
        """
        import functools

        import jax
        import jax.numpy as jnp
        from jax import lax

        from ..ops import embeddings as E

        # Table-update lowering: scatter-add everywhere (round-3 shootout,
        # ops/embeddings.py module docstring).
        dense = len(self.vocab) <= E.DENSE_UPDATE_MAX_ROWS
        is_cbow = self.algorithm == "cbow"
        use_hs = self.use_hs
        V, K, B = len(self.vocab), self.negative, self.batch_size
        if use_hs:
            points_d, codes_d, mask_d = hs_dev
        else:
            lab = jnp.zeros((B, 1 + K), jnp.float32).at[:, 0].set(1.0)

        def pm_of(nv):
            return (lax.broadcasted_iota(jnp.int32, (B,), 0)
                    < nv).astype(jnp.float32)

        def body(carry, inp):
            s0, s1 = carry
            if is_cbow and use_hs:
                ctx, cm, c, nv, lr = inp
                c = c.astype(jnp.int32)
                s0, s1, loss = E.cbow_hs(
                    s0, s1, ctx.astype(jnp.int32), cm.astype(jnp.float32),
                    points_d[c], codes_d[c], mask_d[c], lr, pm_of(nv),
                    dense=dense)
            elif is_cbow:
                ctx, cm, tgt, nv, lr = inp
                s0, s1, loss = E.cbow(
                    s0, s1, ctx.astype(jnp.int32), cm.astype(jnp.float32),
                    tgt, lab, lr, pm_of(nv), dense=dense)
            elif use_hs:
                c, x, nv, lr = inp
                x = x.astype(jnp.int32)
                s0, s1, loss = E.skipgram_hs(
                    s0, s1, c.astype(jnp.int32), points_d[x], codes_d[x],
                    mask_d[x], lr, pm_of(nv), dense=dense)
            else:
                c, tgt, nv, lr = inp
                s0, s1, loss = E.skipgram(
                    s0, s1, c.astype(jnp.int32), tgt, lab, lr, pm_of(nv),
                    dense=dense)
            return (s0, s1), (loss, nv.astype(jnp.float32))

        def bulk_targets(key, pos3):
            """[R, B, 1+K] int32 targets for the whole block (col 0 =
            positive); collisions with the positive shifted by one (same
            shift the host path uses)."""
            T = ntable_dev.shape[0]
            bits = jax.random.bits(key, pos3.shape + (K,), jnp.uint32)
            negs = ntable_dev[(bits & (T - 1)).astype(jnp.int32)]
            negs = jnp.where(negs == pos3[..., None], (negs + 1) % V, negs)
            return jnp.concatenate([pos3[..., None], negs], axis=-1)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def block(syn0, syn1, cols, key, blk_id):
            # fold_in runs INSIDE the jit: eager jax.random.fold_in is a
            # chain of tiny dispatches, each paying ~95 ms of relay latency
            # (round-3 measurement) — hoisting it makes the whole block one
            # dispatch again.
            key = jax.random.fold_in(key, blk_id)
            if use_hs:
                xs = cols
            elif is_cbow:
                ctx3, cm3, c3, nv3, lr3 = cols
                tgt3 = bulk_targets(key, c3.astype(jnp.int32))
                xs = (ctx3, cm3, tgt3, nv3, lr3)
            else:
                c3, x3, nv3, lr3 = cols
                tgt3 = bulk_targets(key, x3.astype(jnp.int32))
                xs = (c3, tgt3, nv3, lr3)
            (syn0, syn1), (losses, ns) = lax.scan(body, (syn0, syn1), xs)
            # pair-weighted mean: mask-padded rounds carry zero weight, so
            # the monitored loss tracks training regardless of padding
            return (syn0, syn1,
                    (losses * ns).sum() / jnp.maximum(ns.sum(), 1.0))

        return xprof.register_jit("nlp/w2v_sg_block", block, donate=(0, 1))

    def _make_window_block(self, hs_dev=None, ntable_dev=None):
        """Packed device-windowed skip-gram block: the corpus lives ON
        DEVICE, each dispatch derives its training pairs there AND compacts
        them densely before training.

        Jitted ``(syn0, syn1, ids, sent, n_valid, p0, (lr0, lr1), key,
        blk_id) -> (syn0', syn1', mean_loss, n_pairs)`` where ``ids``/
        ``sent`` are the (subsampled, compacted) flat corpus and its
        sentence-id map — uploaded once per epoch, ~2–6 bytes/word — and
        per-dispatch host traffic is three scalars. Round-3's design
        trained every candidate slot with a validity mask: reduced windows
        (b ~ U[1, W]) plus boundary losses left only ~53% of slots live, so
        nearly half the gather/scatter bandwidth moved masked zeros
        (BASELINE.md round-3 audit; VERDICT r3 weak #1). This block instead:

        1. derives ALL candidate pairs for a span of S = B·R/(W+1)
           positions (S·2W candidate slots) in one vectorized pass;
        2. compacts the valid (center, context) pairs with a
           cumsum→scatter into a dense buffer of capacity ⌈S·2W/B⌉·B —
           the worst case (every position realizing its full 2W window),
           so NO pair can ever be dropped; the span size S targets the
           EXPECTED fill E[min(b,left)+min(b,right)] ≤ E[2b] = W+1 pairs
           per position ≈ R dense rounds;
        3. trains ceil(count/B) fully-dense rounds under a
           ``lax.while_loop`` — unfilled capacity never executes, and the
           single partial tail round wastes <1% instead of 47%.

        Dense packing is pure bookkeeping (≈8 bytes/slot) next to a
        training round (≈4·(2+K)·D bytes/slot of table gather+scatter), so
        compaction costs ~1% and the masked-slot waste converts almost
        entirely into throughput. The statistical procedure (reduced
        windows, subsampled stream, NS/HS paths, linear LR decay, corpus
        pair order) is unchanged from round 3.
        """
        import functools

        import jax
        import jax.numpy as jnp
        from jax import lax

        from ..ops import embeddings as E

        is_hs = self.use_hs
        V, K, W = len(self.vocab), self.negative, self.window
        B = self._round_pairs                # dense pairs per round
        R = self.MAX_BLOCK_ROUNDS
        S = self._window_span                # positions per dispatch
        # worst-case capacity (every slot valid), rounded up to full rounds
        C = -(-(S * 2 * W) // B) * B
        if is_hs:
            points_d, codes_d, mask_d = hs_dev
            self._win_negpool = jnp.zeros((8,), jnp.int32)
        else:
            lab = jnp.zeros((B, 1 + K), jnp.float32).at[:, 0].set(1.0)
            # Pre-drawn negative POOL, walked with a prime stride per round
            # instead of a per-dispatch C×K table gather (round-4 trace:
            # that gather cost MORE than the training loop). word2vec.c
            # itself walks its 1e8-slot table with an LCG — a fixed
            # pseudo-random pool consumed at pseudo-random offsets is the
            # same statistical device, built from the unigram^0.75 table.
            self._win_negpool = self._build_negpool(ntable_dev, B * K)

        def pack(ids, sent, n_valid, p0, kb):
            """Shared ``_pack_span`` (see its docstring): derive + compact
            this span's pairs → ([C] centers, [C] contexts, count)."""
            return _pack_span(ids, sent, n_valid, p0, S, W, C, kb)

        shard_axis = (self.table_sharding_axis if self.mesh is not None
                      else None)

        def block_fn(syn0, syn1, ids, sent, n_valid, negpool, p0, lr01, key,
                     blk_id):
            key = jax.random.fold_in(key, blk_id)
            packed_c, packed_x, count = pack(ids, sent, n_valid, p0, key)
            lr0, lr1 = lr01
            countf = jnp.maximum(count.astype(jnp.float32), 1.0)

            def cond(st):
                return st[0] * B < count

            def body(st):
                r, s0, s1, lsum, wsum = st
                c = lax.dynamic_slice(packed_c, (r * B,), (B,))
                x = lax.dynamic_slice(packed_x, (r * B,), (B,))
                pm = ((lax.broadcasted_iota(jnp.int32, (B,), 0) + r * B)
                      < count).astype(jnp.float32)
                # linear LR interpolation across the dispatch (reference
                # updates alpha every 10k words — same granularity class)
                lr = lr0 + (lr1 - lr0) * (r * B).astype(jnp.float32) / countf
                if is_hs:
                    s0, s1, loss = E.skipgram_hs(
                        s0, s1, c, points_d[x], codes_d[x], mask_d[x],
                        lr, pm, dense=False)
                else:
                    negs = _pool_negs(negpool, blk_id, r, B, K, V, x)
                    tgt = jnp.concatenate([x[:, None], negs], axis=1)
                    if shard_axis is not None:
                        s0, s1, loss = E.sharded_skipgram(
                            s0, s1, c, tgt, lab, lr, pm, axis=shard_axis)
                    else:
                        s0, s1, loss = E.skipgram(s0, s1, c, tgt, lab, lr,
                                                  pm, dense=False)
                return (r + 1, s0, s1, lsum + loss * pm.sum(),
                        wsum + pm.sum())

            init = (jnp.int32(0), syn0, syn1, jnp.float32(0.0),
                    jnp.float32(0.0))
            _, syn0, syn1, lsum, wsum = lax.while_loop(cond, body, init)
            return (syn0, syn1, lsum / jnp.maximum(wsum, 1.0), wsum)

        if shard_axis is None:
            return xprof.register_jit(
                "nlp/w2v_table_block",
                jax.jit(block_fn, donate_argnums=(0, 1)), donate=(0, 1))
        # sharded tables: the pack + negatives run REPLICATED (all inputs
        # replicated, deterministic ops), only table rows live split
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        tspec = P(shard_axis, None)
        sharded = shard_map(
            block_fn, mesh=self.mesh,
            in_specs=(tspec, tspec, P(), P(), P(), P(), P(), P(), P(), P()),
            out_specs=(tspec, tspec, P(), P()),
            check_rep=False)
        return xprof.register_jit(
            "nlp/w2v_table_block",
            jax.jit(sharded, donate_argnums=(0, 1)), donate=(0, 1))

    @property
    def _cbow_centers(self) -> int:
        """Examples per device-windowed CBOW round (same tiny-vocab
        stability cap rationale as ``_round_pairs``; same HS root-row
        collision cap)."""
        cap = min(self.batch_size, 8 * max(len(self.vocab), 1))
        if self.use_hs:
            cap = min(cap, self.HS_MAX_ROUND)
        return max(1, cap)

    # -- shared device-window helpers (skip-gram + CBOW blocks) ----------
    def _build_negpool(self, ntable_dev, round_negs: int):
        """Pre-drawn negative pool (see _make_window_block docstring);
        shared by both windowed blocks so the stride/seed/size contracts
        cannot drift between algorithms."""
        import jax
        import jax.numpy as jnp

        if round_negs >= self.NEG_POOL_SIZE:
            raise ValueError(
                f"negatives per round ({round_negs}) must be below "
                f"NEG_POOL_SIZE={self.NEG_POOL_SIZE}; lower batch_size/"
                "negative or raise NEG_POOL_SIZE")
        T = ntable_dev.shape[0]
        kp = jax.random.PRNGKey((self.seed ^ 0x5DEECE66) & 0x7FFFFFFF)
        bits = jax.random.bits(kp, (self.NEG_POOL_SIZE,), jnp.uint32)
        return ntable_dev[(bits & (T - 1)).astype(jnp.int32)]

    def _make_cbow_window_block(self, hs_dev=None, ntable_dev=None):
        """Device-windowed CBOW block (round-4): the corpus lives on
        device and every dispatch derives a span of S = B_C·R center
        positions' context windows there — contexts from 2W shifted
        slices, masked mean in the kernel. Unlike skip-gram there is
        nothing to compact: every in-bounds position IS one example, so a
        plain fixed-R ``lax.scan`` is already dense (examples whose
        reduced window is empty carry pair-mask 0). Negatives ride the
        same pre-drawn pool as the skip-gram block. Statistical procedure
        matches the host CBOW path (reduced windows, masked mean,
        NS/HS on the center word)."""
        import functools

        import jax
        import jax.numpy as jnp
        from jax import lax

        from ..ops import embeddings as E

        is_hs = self.use_hs
        V, K, W = len(self.vocab), self.negative, self.window
        B_C = self._cbow_centers
        R = self.MAX_BLOCK_ROUNDS
        S = B_C * R
        if is_hs:
            points_d, codes_d, mask_d = hs_dev
            self._win_negpool = jnp.zeros((8,), jnp.int32)
        else:
            lab = jnp.zeros((B_C, 1 + K), jnp.float32).at[:, 0].set(1.0)
            self._win_negpool = self._build_negpool(ntable_dev, B_C * K)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def block(syn0, syn1, ids, sent, n_valid, negpool, p0, lr01, key,
                  blk_id):
            key = jax.random.fold_in(key, blk_id)
            c_ids, ctx_all, valid, live = _derive_windows(
                ids, sent, n_valid, p0, S, W, key)
            cm_all = valid.astype(jnp.float32)
            lr0, lr1 = lr01

            def body(carry, r):
                s0, s1 = carry
                sl = r * B_C
                c = lax.dynamic_slice(c_ids, (sl,), (B_C,))
                cx = lax.dynamic_slice(ctx_all, (sl, jnp.int32(0)),
                                       (B_C, 2 * W))
                cm = lax.dynamic_slice(cm_all, (sl, jnp.int32(0)),
                                       (B_C, 2 * W))
                lv = lax.dynamic_slice(live, (sl,), (B_C,))
                pm = (lv & (cm.sum(axis=1) > 0)).astype(jnp.float32)
                lr = lr0 + (lr1 - lr0) * r.astype(jnp.float32) / R
                if is_hs:
                    s0, s1, loss = E.cbow_hs(
                        s0, s1, cx, cm, points_d[c], codes_d[c],
                        mask_d[c], lr, pm, dense=False)
                else:
                    negs = _pool_negs(negpool, blk_id, r, B_C, K, V, c)
                    tgt = jnp.concatenate([c[:, None], negs], axis=1)
                    s0, s1, loss = E.cbow(s0, s1, cx, cm, tgt, lab, lr,
                                          pm, dense=False)
                return (s0, s1), (loss, pm.sum())

            (syn0, syn1), (losses, ns) = lax.scan(
                body, (syn0, syn1), jnp.arange(R, dtype=jnp.int32))
            return (syn0, syn1,
                    (losses * ns).sum() / jnp.maximum(ns.sum(), 1.0),
                    ns.sum())

        return xprof.register_jit("nlp/w2v_cbow_block", block,
                                  donate=(0, 1))

    def _block_for(self, tag: str, make: Callable, *extra):
        """Shared block-function cache: rebuild (re-trace) only when the
        config/vocab the closure captures actually changed. ``make``
        receives ``(hs_dev, ntable_dev)`` device tables. Keyed BY TAG so
        paths that alternate two blocks in one fit (ParagraphVectors DBOW
        + word skip-gram) don't thrash a single slot."""
        import jax.numpy as jnp

        # content hash (not just len/sum): two rebuilt vocabs with equal size
        # and total count must not reuse stale Huffman paths / unigram tables
        counts = np.ascontiguousarray(self.vocab.counts())
        key = (len(self.vocab), hash(counts.tobytes()),
               self.negative, self.algorithm, self.use_hs) + extra
        cache = getattr(self, "_block_cache", None)
        if cache is None:
            cache = self._block_cache = {}
        if tag not in cache or cache[tag][0] != key:
            hs_dev = ntable_dev = None
            if self.use_hs:
                hs_codes, hs_points, hs_mask = huffman_arrays(self.vocab)
                hs_dev = (jnp.asarray(hs_points), jnp.asarray(hs_codes),
                          jnp.asarray(hs_mask))
            else:
                ntable_dev = jnp.asarray(unigram_int_table(self.vocab))
            cache[tag] = (key, make(hs_dev, ntable_dev))
        return cache[tag][1]

    def _train_windowed(self, corpus: List[np.ndarray],
                        total_words: Optional[int] = None) -> None:
        """Device-resident-corpus fit for BOTH algorithms: skip-gram
        (``_make_window_block``, dense-packed pairs) and CBOW
        (``_make_cbow_window_block``, one example per position).
        Statistical procedure matches the host path: frequent-word
        subsampling + stream compaction per epoch (ON DEVICE since round
        4 — ``_subsample_fn``, keyed off a dedicated fold of the base
        key), reduced windows, NS from the unigram^0.75 pool or HS
        Huffman paths, linear LR decay by corpus-words consumed."""
        import jax
        import jax.numpy as jnp

        keep = subsample_keep_probs(self.vocab, self.sampling)
        raw_words = sum(len(s) for s in corpus)
        if total_words is None:
            total_words = raw_words * self.epochs * self.iterations

        is_cbow = self.algorithm == "cbow"
        if is_cbow and self.mesh is not None:
            raise ValueError("sharded tables support the skip-gram "
                             "windowed path only (no sharded CBOW kernel)")
        if is_cbow:
            block = self._block_for("cwin", self._make_cbow_window_block,
                                    self.window, self._cbow_centers)
        else:
            block = self._block_for("win", self._make_window_block,
                                    self.window, self._window_centers,
                                    None if self.mesh is None
                                    else (id(self.mesh),
                                          self.table_sharding_axis))

        flat = (np.concatenate(corpus) if corpus
                else np.empty(0, np.int32)).astype(np.int32)
        lens = np.array([c.size for c in corpus], dtype=np.int64)
        # Sentence ids travel as uint16 via mod-65535: the boundary check
        # only compares positions ≤ W apart, whose true sentence ids differ
        # by ≤ W < 65535, so modular equality is EXACT. 65535 is the pad
        # sentinel (never a real id), making boundary checks fail in the
        # pad region.
        assert self.window < 65535
        sent_full = (np.repeat(np.arange(len(corpus), dtype=np.int64), lens)
                     % 65535).astype(np.uint16)
        idx_dt = (np.uint16 if len(self.vocab) <= (1 << 16) else np.int32)
        sent_dt = np.uint16

        base_key = jax.random.PRNGKey(self.seed)
        tdt = (jnp.bfloat16 if getattr(self, "table_dtype", "float32")
               == "bfloat16" else jnp.float32)
        syn1_host = (self.lookup_table.syn1 if self.use_hs
                     else self.lookup_table.syn1neg)
        V = len(self.vocab)
        if self.mesh is not None:
            # row-shard the tables over the mesh axis (zero-padded to a
            # shard multiple; pad rows are unreachable — ids < V)
            from jax.sharding import NamedSharding, PartitionSpec as P

            n_sh = self.mesh.shape[self.table_sharding_axis]
            Vp = -(-V // n_sh) * n_sh
            tsh = NamedSharding(self.mesh, P(self.table_sharding_axis,
                                             None))
            self._repl_sharding = NamedSharding(self.mesh, P())

            def place(t):
                padded = np.zeros((Vp, t.shape[1]), np.float32)
                padded[:V] = np.asarray(t)
                return jax.device_put(jnp.asarray(padded, tdt), tsh)

            syn0, syn1 = place(self.lookup_table.syn0), place(syn1_host)
        else:
            self._repl_sharding = None
            syn0 = jnp.asarray(self.lookup_table.syn0, tdt)
            syn1 = jnp.asarray(syn1_host, tdt)
        losses, pair_counts = [], []
        n_blocks = 0
        words_seen = 0
        t0 = time.perf_counter()

        # --- corpus → device, ONCE per distinct corpus (cached across
        # fits: the bench/resume pattern re-fits the same corpus, and the
        # relay link is the scarce resource — BASELINE.md). Frequent-word
        # subsampling then runs ON DEVICE each epoch (round-4 change): the
        # round-3 design re-uploaded the host-subsampled stream every
        # epoch (~4 bytes/word/epoch ≈ seconds of relay time per epoch at
        # packed-path training rates), which had become the bottleneck.
        # Layout: [W sentinel front-pad][stream][sentinel tail] — the
        # front pad lets the pack derive windows from shifted slices.
        W = self.window
        npad = -(-max(flat.size, 1) // self.CORPUS_BUCKET) \
            * self.CORPUS_BUCKET
        span = (self._cbow_centers * self.MAX_BLOCK_ROUNDS if is_cbow
                else self._window_span)   # positions per dispatch
        buf_len = npad + span + 2 * W
        ckey = (flat.size, hash(flat.tobytes()), buf_len, str(idx_dt),
                None if self.mesh is None else id(self.mesh))
        cached = getattr(self, "_corpus_dev_cache", None)
        if cached is not None and cached[0] == ckey:
            ids_full, sent_full_dev = cached[1]
        else:
            ids_np = np.zeros(buf_len, idx_dt)
            ids_np[W:W + flat.size] = flat.astype(idx_dt)
            sent_np = np.full(buf_len, np.iinfo(sent_dt).max, sent_dt)
            sent_np[W:W + flat.size] = sent_full
            ids_full = jax.device_put(ids_np, self._repl_sharding)
            sent_full_dev = jax.device_put(sent_np, self._repl_sharding)
            self._corpus_dev_cache = (ckey, (ids_full, sent_full_dev))
        if self.mesh is not None:
            self._win_negpool = jax.device_put(self._win_negpool,
                                               self._repl_sharding)
        n_raw = flat.size

        if self.sampling > 0:
            keep_dev = jnp.asarray(keep.astype(np.float32))
            subsample = self._subsample_fn()
            ksub_base = jax.random.fold_in(base_key, (1 << 31) - 1)
            # Host-side expectations pace the LR and bound the dispatch
            # loop WITHOUT reading the device count back (no sync): the
            # realized count exceeds E+6σ with probability ~1e-9 (binomial
            # tail); the sub-span tail beyond the bound would lose <1e-5
            # of one epoch's positions even then.
            kf = keep[flat]
            n_exp = float(kf.sum())
            n_loop = min(n_raw, int(n_exp + 6.0 * np.sqrt(
                max(float((kf * (1.0 - kf)).sum()), 1.0)) + 1))
        else:
            n_exp = float(n_raw)
            n_loop = n_raw

        def lr_at(frac: float) -> np.float32:
            return np.float32(max(
                self.learning_rate * (1.0 - min(frac, 1.0)),
                self.min_learning_rate))

        for _epoch in range(self.epochs):
            if self.sampling > 0:
                ids_dev, sent_dev, n_valid = subsample(
                    ids_full, sent_full_dev, keep_dev, np.int32(n_raw),
                    jax.random.fold_in(ksub_base, _epoch))
            else:
                ids_dev, sent_dev = ids_full, sent_full_dev
                n_valid = np.int32(n_raw)
            for _it in range(self.iterations):
                it_base = words_seen
                for p0 in range(0, n_loop, span):
                    # LR decays by raw corpus words consumed; compacted
                    # position p maps to ~p/n_exp of this epoch-pass's
                    # words. The block interpolates linearly between the
                    # span's start/end rates on device.
                    lr0 = lr_at((it_base + p0 / max(n_exp, 1.0) * raw_words)
                                / max(total_words, 1))
                    lr1 = lr_at((it_base
                                 + min(p0 + span, n_loop) / max(n_exp, 1.0)
                                 * raw_words) / max(total_words, 1))
                    syn0, syn1, loss, np_ = block(
                        syn0, syn1, ids_dev, sent_dev, n_valid,
                        self._win_negpool, np.int32(p0), (lr0, lr1),
                        base_key, np.int32(n_blocks))
                    n_blocks += 1
                    losses.append(loss)
                    pair_counts.append(np_)
                words_seen += raw_words
        # VALUE fence (see _train_encoded): read back results that depend
        # on the full chain, once.
        last = (np.asarray(jnp.stack(losses[-50:])) if losses
                else np.zeros(1, np.float32))
        pairs_seen = (float(np.asarray(jnp.stack(pair_counts)).sum())
                      if pair_counts else 0.0)
        dt = time.perf_counter() - t0
        self.words_per_sec = words_seen / max(dt, 1e-9)
        self.pairs_per_sec = pairs_seen / max(dt, 1e-9)
        self.last_loss = float(last.mean()) if losses else 0.0
        # strip to the TABLE's row count: drops the shard-padding rows of
        # a mesh-sharded fit, but keeps FastText's n-gram bucket rows
        # (lookup_table.vocab_size = V + bucket there)
        n_rows = self.lookup_table.vocab_size or len(self.vocab)
        self.lookup_table.syn0 = np.asarray(syn0.astype(jnp.float32))[:n_rows]
        if self.use_hs:
            self.lookup_table.syn1 = np.asarray(
                syn1.astype(jnp.float32))[:n_rows]
        else:
            self.lookup_table.syn1neg = np.asarray(
                syn1.astype(jnp.float32))[:n_rows]

    def _train_encoded(self, corpus: List[np.ndarray],
                       stream_factory: Optional[Callable] = None,
                       total_words: Optional[int] = None) -> None:
        """Run the full fit over an encoded corpus.

        ``stream_factory(rng, keep)`` (optional) overrides per-sentence batch
        generation — it must yield ``(centers, contexts)`` tuples for
        skip-gram configs or ``(centers, ctx, cmask)`` for CBOW configs.
        ParagraphVectors uses this to inject doc-label ids into the stream.

        Plain fits (no custom stream) — skip-gram AND CBOW — use the
        device-windowed path (``_train_windowed``): corpus resident on
        device, windows derived there. Custom streams (ParagraphVectors)
        use the host pair pipeline below (native ``sg_pairs`` C++ producer
        + background staging); ``device_corpus=False`` on the instance
        forces the host path for either algorithm.
        """
        import jax.numpy as jnp

        import jax

        if (stream_factory is None
                and getattr(self, "device_corpus", True)):
            # both algorithms ride the device-windowed corpus (round 4:
            # CBOW derives its windows on device too)
            return self._train_windowed(corpus, total_words)
        if getattr(self, "mesh", None) is not None:
            raise ValueError(
                "sharded tables (mesh=...) are implemented for the "
                "device-windowed paths only — custom streams "
                "(ParagraphVectors) and device_corpus=False would "
                "silently train unsharded")

        rng = np.random.default_rng(self.seed)
        keep = subsample_keep_probs(self.vocab, self.sampling)
        block = self._block_for("host", self._make_block, self.batch_size)
        base_key = jax.random.PRNGKey(self.seed)
        n_blocks = 0
        V = len(self.vocab)
        B, K = self.batch_size, self.negative
        if total_words is None:
            total_words = (sum(len(s) for s in corpus)
                           * self.epochs * self.iterations)
        syn0 = jnp.asarray(self.lookup_table.syn0)
        syn1 = jnp.asarray(self.lookup_table.syn1 if self.use_hs
                           else self.lookup_table.syn1neg)

        is_cbow = self.algorithm == "cbow"
        words_seen = 0     # corpus words consumed (drives the LR schedule)
        pairs_seen = 0     # training examples executed on device
        losses = []
        t0 = time.perf_counter()

        def _lr() -> np.float32:
            # Linear decay by CORPUS WORDS CONSUMED (word2vec.c semantics:
            # alpha decays with corpus progress, not with pair count).
            frac = min(words_seen / max(total_words, 1), 1.0)
            return np.float32(max(self.learning_rate * (1 - frac),
                                  self.min_learning_rate))

        # uint16 indices on the wire whenever the TABLE fits (the relay
        # moves 5-10 MB/s; bytes ARE throughput — see _make_block). The
        # table can be taller than the vocab: FastText streams subword row
        # ids up to V + bucket, so sizing off len(vocab) alone would wrap
        # ids >= 2^16.
        n_rows = self.lookup_table.vocab_size or V
        idx_dt = np.uint16 if n_rows <= (1 << 16) else np.int32

        def _rounds(npairs):
            """Pad-to-a-multiple-of-a-full-block bookkeeping shared by
            both flushes. Padded pairs are masked out on DEVICE from the
            per-round valid count ``nv``."""
            pad = (-npairs) % (B * self.MAX_BLOCK_ROUNDS)
            R = (npairs + pad) // B
            nv = np.minimum(np.maximum(npairs - np.arange(R) * B, 0),
                            B).astype(np.int32)
            return pad, nv, R

        def _blocks(R):
            """Split R rounds (a multiple of MAX_BLOCK_ROUNDS) into
            full-sized scanned blocks — ONE compiled shape per fit."""
            for r in range(0, R, self.MAX_BLOCK_ROUNDS):
                yield r, self.MAX_BLOCK_ROUNDS

        def _stage(cols):
            """Upload a block's columns from the PRODUCER thread so H2D
            transfer overlaps the consumer's device dispatches."""
            return tuple(jax.device_put(a) for a in cols)

        def flush_sg(centers, contexts):
            nonlocal pairs_seen
            npairs = centers.size
            pad, nv, R = _rounds(npairs)
            c3 = np.pad(centers.astype(idx_dt), (0, pad)).reshape(R, B)
            x3 = np.pad(contexts.astype(idx_dt), (0, pad)).reshape(R, B)
            lr = _lr()
            pairs_seen += npairs
            for r, nb in _blocks(R):
                sl = slice(r, r + nb)
                yield _stage((c3[sl], x3[sl], nv[sl],
                              np.full(nb, lr, np.float32)))

        def flush_cbow(centers, ctx, cmask):
            nonlocal pairs_seen
            npairs = centers.size
            pad, nv, R = _rounds(npairs)
            W = ctx.shape[1]
            c3 = np.pad(centers.astype(idx_dt), (0, pad)).reshape(R, B)
            ctx3 = np.pad(ctx.astype(idx_dt),
                          ((0, pad), (0, 0))).reshape(R, B, W)
            cm3 = np.pad(cmask.astype(np.uint8),
                         ((0, pad), (0, 0))).reshape(R, B, W)
            lr = _lr()
            pairs_seen += npairs
            for r, nb in _blocks(R):
                sl = slice(r, r + nb)
                yield _stage((ctx3[sl], cm3[sl], c3[sl], nv[sl],
                              np.full(nb, lr, np.float32)))

        def default_stream(rng, keep):
            if is_cbow:
                for ids in corpus:
                    wins = self._sentence_windows(ids, rng, keep)
                    if wins is not None:
                        yield (ids.size,) + wins
                return
            # skip-gram pair generation: one native call per sentence chunk
            # (libdatavec_native, SURVEY §7.1.2 "native where the reference
            # is native") with the numpy per-sentence path as fallback
            from .. import native

            if native.available():
                CHUNK = 2048
                keep_arr = keep if self.sampling > 0 else None
                for s0 in range(0, len(corpus), CHUNK):
                    chunk = corpus[s0:s0 + CHUNK]
                    offsets = np.zeros(len(chunk) + 1, np.int64)
                    np.cumsum([c.size for c in chunk], out=offsets[1:])
                    flat = np.concatenate(chunk) if chunk else \
                        np.empty(0, np.int32)
                    c, x = native.sg_pairs(
                        flat, offsets, self.window, keep_arr,
                        int(rng.integers(1, 2 ** 63 - 1)))
                    if c.size:
                        yield int(offsets[-1]), c, x
                return
            for ids in corpus:
                pairs = self._sentence_pairs(ids, rng, keep)
                if pairs is not None:
                    yield (ids.size,) + pairs

        if stream_factory is None:
            stream_factory = default_stream

        def work_items():
            """Producer generator: pair generation + batching + padding on
            the host, yielding ready column blocks. Runs on a background
            thread (``prefetch_iter``) so pair-gen for flush N+1 overlaps
            the device executing flush N — the TPU analog of the
            reference's N worker threads keeping the JNI kernels fed."""
            nonlocal words_seen
            # Mid-fit flushes emit only FULL MAX_BLOCK_ROUNDS blocks and
            # carry the remainder pairs forward (even across epochs): tail
            # blocks pay upload fixed-costs out of proportion to their
            # size, so exactly one padded tail runs — at the very end.
            chunk = self.MAX_BLOCK_ROUNDS * B
            if is_cbow:
                buf = []
                buffered = 0
                for _epoch in range(self.epochs):
                    for item in stream_factory(rng, keep):
                        nwords, wins = item[0], item[1:]
                        words_seen += nwords * self.iterations
                        for _ in range(self.iterations):
                            buf.append(wins)
                            buffered += wins[0].size
                        if buffered >= chunk:
                            c, ctx, cm = (np.concatenate([w[i] for w in buf])
                                          for i in range(3))
                            n_full = (c.shape[0] // chunk) * chunk
                            yield from flush_cbow(c[:n_full], ctx[:n_full],
                                                  cm[:n_full])
                            buf = [(c[n_full:], ctx[n_full:], cm[n_full:])]
                            buffered = c.shape[0] - n_full
                if buffered:
                    yield from flush_cbow(
                        np.concatenate([w[0] for w in buf]),
                        np.concatenate([w[1] for w in buf]),
                        np.concatenate([w[2] for w in buf]))
            else:
                buf_c: List[np.ndarray] = []
                buf_x: List[np.ndarray] = []
                buffered = 0
                for _epoch in range(self.epochs):
                    for item in stream_factory(rng, keep):
                        nwords, pairs = item[0], item[1:]
                        words_seen += nwords * self.iterations
                        for _ in range(self.iterations):
                            buf_c.append(pairs[0])
                            buf_x.append(pairs[1])
                            buffered += pairs[0].size
                        if buffered >= chunk:
                            c = np.concatenate(buf_c)
                            x = np.concatenate(buf_x)
                            n_full = (c.size // chunk) * chunk
                            yield from flush_sg(c[:n_full], x[:n_full])
                            buf_c, buf_x = [c[n_full:]], [x[n_full:]]
                            buffered = c.size - n_full
                if buffered:
                    yield from flush_sg(np.concatenate(buf_c),
                                        np.concatenate(buf_x))

        from ..common.background import prefetch_iter

        for cols in prefetch_iter(work_items(), maxsize=8):
            syn0, syn1, loss = block(syn0, syn1, cols, base_key,
                                     np.int32(n_blocks))
            n_blocks += 1
            losses.append(loss)   # device scalar; no sync in the loop

        # VALUE fence: through the TPU relay block_until_ready returns
        # before device work completes (BASELINE.md round-2 methodology
        # note); reading back a value that depends on the whole chain is
        # the honest barrier. One stacked readback also replaces the 50
        # per-scalar syncs the loss average used to pay.
        last = (np.asarray(jnp.stack(losses[-50:])) if losses
                else np.zeros(1, np.float32))
        dt = time.perf_counter() - t0
        self.words_per_sec = words_seen / max(dt, 1e-9)
        self.pairs_per_sec = pairs_seen / max(dt, 1e-9)
        self.last_loss = float(last.mean()) if losses else 0.0
        self.lookup_table.syn0 = np.asarray(syn0)
        if self.use_hs:
            self.lookup_table.syn1 = np.asarray(syn1)
        else:
            self.lookup_table.syn1neg = np.asarray(syn1)

    @staticmethod
    def _neg_targets(pos: np.ndarray, rng: np.random.Generator,
                     cdf: np.ndarray, V: int, K: int):
        """[B, 1+K] targets (col 0 = positive) + labels; negatives drawn
        from the unigram^0.75 CDF, collisions with the positive shifted by
        one (the reference resamples; a deterministic shift is unbiased to
        O(1/V) and keeps the host path branch-free)."""
        B = pos.shape[0]
        negs = np.searchsorted(cdf, rng.random((B, K))).astype(np.int32)
        negs = np.where(negs == pos[:, None], (negs + 1) % V, negs)
        targets = np.concatenate([pos[:, None], negs], axis=1)
        labels = np.zeros((B, 1 + K), dtype=np.float32)
        labels[:, 0] = 1.0
        return targets, labels


def _derive_windows(ids, sent, n_valid, p0, S, W, key):
    """Shared device window derivation for the windowed blocks: one
    contiguous dynamic-slice window (buffers carry W front-pad sentinel
    slots; stream position p = buffer index p+W), contexts as 2W STATIC
    shifted slices, validity from reduced window b ~ U[1, W] + sentence
    equality + stream bounds. Returns (c_ids [S], ctx [S, 2W],
    valid [S, 2W] bool, live [S] bool)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    idw = lax.dynamic_slice(ids, (p0,), (S + 2 * W,)).astype(jnp.int32)
    sw = lax.dynamic_slice(sent, (p0,), (S + 2 * W,)).astype(jnp.int32)
    c_ids = idw[W:W + S]
    c_sent = sw[W:W + S]
    p = p0 + lax.broadcasted_iota(jnp.int32, (S,), 0)
    live = p < n_valid
    b = jax.random.randint(key, (S,), 1, W + 1)
    ctx_cols, v_cols = [], []
    for o in list(range(-W, 0)) + list(range(1, W + 1)):
        ctx_cols.append(idw[W + o:W + o + S])
        v_cols.append((b >= abs(o)) & live
                      & (sw[W + o:W + o + S] == c_sent))
    return (c_ids, jnp.stack(ctx_cols, 1), jnp.stack(v_cols, 1), live)


def _pack_span(ids, sent, n_valid, p0, S, W, C, key):
    """Derive + densely compact a span's skip-gram pairs → ([C] centers,
    [C] contexts, count). Window derivation is the shared
    ``_derive_windows`` (shifted slices — the round-3 element-granular
    ids[q] gathers were the single most expensive fusion in the device
    trace). Compaction is an order-preserving cumsum→scatter, so pairs
    train in corpus order. Shared by the skip-gram windowed block and
    FastText's subword block."""
    import jax.numpy as jnp

    c_ids, x_ids, valid, _ = _derive_windows(ids, sent, n_valid, p0, S, W,
                                             key)
    vf = valid.reshape(-1)
    dest = jnp.cumsum(vf.astype(jnp.int32)) - 1
    count = jnp.minimum(dest[-1] + 1, C)
    slot = jnp.where(vf, dest, C)               # C = dropped
    packed_c = jnp.zeros((C,), jnp.int32).at[slot].set(
        jnp.broadcast_to(c_ids[:, None], (S, 2 * W)).reshape(-1),
        mode="drop")
    packed_x = jnp.zeros((C,), jnp.int32).at[slot].set(
        x_ids.reshape(-1), mode="drop")
    return packed_c, packed_x, count


def _pool_negs(negpool, blk_id, r, B, K, V, positives):
    """Stride-walk a [B, K] window of the pre-drawn pool for round ``r``
    of dispatch ``blk_id`` and collision-shift against ``positives``
    (rounds per dispatch < 131; uint32 math so the product wraps safely)."""
    import jax.numpy as jnp
    from jax import lax

    g = blk_id.astype(jnp.uint32) * jnp.uint32(131) + r.astype(jnp.uint32)
    start = ((g * jnp.uint32(48611))
             % jnp.uint32(negpool.shape[0] - B * K)).astype(jnp.int32)
    negs = lax.dynamic_slice(negpool, (start,), (B * K,)).reshape(B, K)
    return jnp.where(negs == positives[:, None], (negs + 1) % V, negs)


class Word2Vec(SequenceVectors):
    """Word2Vec over a sentence corpus (reference: Word2Vec.Builder →
    SequenceVectors.fit, SURVEY §3.6)."""

    class Builder:
        def __init__(self) -> None:
            self._kw = {}
            self._iter: Optional[SentenceIterator] = None
            self._tok: TokenizerFactory = DefaultTokenizerFactory()

        def min_word_frequency(self, v): self._kw["min_word_frequency"] = v; return self
        def iterations(self, v): self._kw["iterations"] = v; return self
        def epochs(self, v): self._kw["epochs"] = v; return self
        def layer_size(self, v): self._kw["layer_size"] = v; return self
        def seed(self, v): self._kw["seed"] = v; return self
        def window_size(self, v): self._kw["window"] = v; return self
        def learning_rate(self, v): self._kw["learning_rate"] = v; return self
        def min_learning_rate(self, v): self._kw["min_learning_rate"] = v; return self
        def negative_sample(self, v): self._kw["negative"] = int(v); return self
        def use_hierarchic_softmax(self, v): self._kw["use_hierarchic_softmax"] = v; return self
        def sampling(self, v): self._kw["sampling"] = v; return self
        def batch_size(self, v): self._kw["batch_size"] = v; return self
        def workers(self, v): self._kw["workers"] = v; return self
        def table_dtype(self, v): self._kw["table_dtype"] = v; return self

        def sharded_tables(self, mesh, axis: str = "model"):
            """Row-shard syn0/syn1 over a mesh axis (the reference's
            VoidParameterServer workload, run as compiled collectives)."""
            self._kw["mesh"] = mesh
            self._kw["table_sharding_axis"] = axis
            return self

        def elements_learning_algorithm(self, name: str):
            self._kw["algorithm"] = \
                "cbow" if "cbow" in name.lower() else "skipgram"
            return self

        def iterate(self, it):
            if isinstance(it, (list, tuple)):
                it = CollectionSentenceIterator(it)
            self._iter = it
            return self

        def tokenizer_factory(self, tf: TokenizerFactory):
            self._tok = tf
            return self

        def build(self) -> "Word2Vec":
            w2v = Word2Vec(**self._kw)
            w2v._sentence_iter = self._iter
            w2v._tokenizer = self._tok
            return w2v

    @staticmethod
    def builder() -> "Word2Vec.Builder":
        return Word2Vec.Builder()

    def __init__(self, **kw):
        super().__init__(**kw)
        self._sentence_iter: Optional[SentenceIterator] = None
        self._tokenizer: TokenizerFactory = DefaultTokenizerFactory()

    def set_sentence_iterator(self, it) -> None:
        if isinstance(it, (list, tuple)):
            it = CollectionSentenceIterator(it)
        self._sentence_iter = it

    def _token_stream(self):
        assert self._sentence_iter is not None, \
            "no corpus: call iterate()/set_sentence_iterator first"
        self._sentence_iter.reset()
        for sentence in self._sentence_iter:
            yield self._tokenizer.create(sentence).get_tokens()

    def fit(self) -> None:
        """Train. First call builds the vocab and initializes tables; a
        model that already has vocab + tables (a second ``fit`` or one
        restored by ``read_word2vec_model``) RESUMES training with the
        existing state — corpus words outside the stored vocab are
        dropped."""
        if len(self.vocab) == 0 or self.lookup_table.syn0 is None:
            self.build_vocab(self._token_stream())
            if len(self.vocab) == 0:
                raise ValueError("empty vocabulary after pruning — lower "
                                 "min_word_frequency or supply more text")
        corpus = self._encode_corpus(self._token_stream())
        self._train_encoded(corpus)
