"""SequenceVectors engine + Word2Vec front (reference: deeplearning4j-nlp
``models/sequencevectors/SequenceVectors`` and ``models/word2vec/Word2Vec``).

Architecture (vs the reference, SURVEY §3.6): the reference trains with N
Java worker threads each dispatching one fused ``SkipGramRound`` JNI kernel
per (center, context) pair. The TPU rebuild keeps the same statistical
procedure — frequency-pruned vocab, frequent-word subsampling, per-position
reduced window, unigram^0.75 negative sampling or Huffman hierarchical
softmax, linear LR decay — but restructures the hot loop hardware-first:

- host side generates training pairs VECTORIZED per sentence (numpy), and
  buffers them into fixed-size batches (static shapes → one compiled
  executable for the whole run);
- device side runs ONE jitted fused round per batch (``ops/embeddings.py``)
  with ``syn0``/``syn1`` donated, so tables live on device for the entire
  fit and nothing transfers but the (tiny) index batches;
- the reference's ``workers`` thread knob is accepted and recorded but
  parallelism comes from batching on the MXU, not host threads.

``iterations`` follows the reference semantics (each sentence's pairs are
trained `iterations` times per epoch); ``epochs`` is the corpus pass count.
"""

from __future__ import annotations

import time
from typing import Callable, Iterable, List, Optional, Sequence

import numpy as np

from .lookup_table import InMemoryLookupTable
from .text import (CollectionSentenceIterator, DefaultTokenizerFactory,
                   SentenceIterator, TokenizerFactory)
from .vocab import (VocabCache, VocabConstructor, build_huffman,
                    huffman_arrays, subsample_keep_probs, unigram_table)


class WordVectors:
    """Query surface shared by Word2Vec/ParagraphVectors and models loaded
    from serialized vectors (reference: WordVectors interface —
    getWordVector / similarity / wordsNearest / accuracy)."""

    def __init__(self, vocab: VocabCache, table: InMemoryLookupTable):
        self.vocab = vocab
        self.lookup_table = table

    # -- basic lookups ----------------------------------------------------
    def has_word(self, word: str) -> bool:
        return word in self.vocab

    def get_word_vector(self, word: str) -> np.ndarray:
        idx = self.vocab.index_of(word)
        if idx < 0:
            raise KeyError(f"word not in vocab: {word!r}")
        return self.lookup_table.vector(idx)

    def get_word_vector_matrix(self) -> np.ndarray:
        return np.asarray(self.lookup_table.syn0)

    # -- similarity / nearest --------------------------------------------
    def similarity(self, w1: str, w2: str) -> float:
        a, b = self.get_word_vector(w1), self.get_word_vector(w2)
        na, nb = np.linalg.norm(a), np.linalg.norm(b)
        if na == 0 or nb == 0:
            return 0.0
        return float(a @ b / (na * nb))

    def words_nearest(self, word_or_vec, top_n: int = 10) -> List[str]:
        if isinstance(word_or_vec, str):
            vec = self.get_word_vector(word_or_vec)
            exclude = {self.vocab.index_of(word_or_vec)}
        else:
            vec = np.asarray(word_or_vec, dtype=np.float32)
            exclude = set()
        w = self.lookup_table.normalized()
        v = vec / max(np.linalg.norm(vec), 1e-12)
        sims = w @ v
        order = np.argsort(-sims)
        out = []
        for idx in order:
            if int(idx) in exclude:
                continue
            out.append(self.vocab.word_for(int(idx)))
            if len(out) == top_n:
                break
        return out

    def accuracy(self, questions: Sequence[Sequence[str]]) -> float:
        """Analogy accuracy: each question is (a, b, c, expected) testing
        b - a + c ≈ expected (reference: WordVectors.accuracy over the
        Google questions-words format)."""
        correct = total = 0
        for a, b, c, expected in questions:
            if not all(self.has_word(w) for w in (a, b, c, expected)):
                continue
            total += 1
            vec = (self.get_word_vector(b) - self.get_word_vector(a)
                   + self.get_word_vector(c))
            nearest = self.words_nearest(vec, top_n=4)
            preds = [w for w in nearest if w not in (a, b, c)]
            if preds and preds[0] == expected:
                correct += 1
        return correct / total if total else 0.0


class SequenceVectors(WordVectors):
    """The distributed-representation training engine; Word2Vec and
    ParagraphVectors are thin configuration fronts over it (mirrors the
    reference's SequenceVectors inheritance)."""

    def __init__(self, *, layer_size: int = 100, window: int = 5,
                 learning_rate: float = 0.025, min_learning_rate: float = 1e-4,
                 negative: int = 5, use_hierarchic_softmax: bool = False,
                 sampling: float = 0.0, min_word_frequency: int = 5,
                 iterations: int = 1, epochs: int = 1, batch_size: int = 512,
                 seed: int = 42, algorithm: str = "skipgram",
                 workers: int = 1,
                 special_tokens: Sequence[str] = ()):
        if use_hierarchic_softmax:
            # DOCUMENTED DIVERGENCE: the reference can train HS and negative
            # sampling simultaneously; this engine trains exactly one output
            # path per fit. Silent dropping would serialize an untrained
            # syn1neg as if it were state — refuse instead.
            if negative == 5:      # the constructor default
                negative = 0
            elif negative > 0:
                raise ValueError(
                    "combined hierarchical-softmax + negative-sampling "
                    "training is not implemented; set negative=0 with "
                    "use_hierarchic_softmax=True (or disable HS)")
        elif negative <= 0:
            raise ValueError("need negative sampling (negative>0) or "
                             "use_hierarchic_softmax=True")
        self.layer_size = layer_size
        self.window = window
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.negative = negative
        self.use_hs = use_hierarchic_softmax
        self.sampling = sampling
        self.min_word_frequency = min_word_frequency
        self.iterations = iterations
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.algorithm = algorithm.lower()
        if self.algorithm not in ("skipgram", "cbow"):
            raise ValueError(f"unknown algorithm {algorithm!r}")
        # Accepted for reference config parity; batching on the MXU replaces
        # host worker threads (see module docstring).
        self.workers = workers
        self._special_tokens = list(special_tokens)
        self.words_per_sec: float = 0.0
        super().__init__(VocabCache(), InMemoryLookupTable(0, layer_size))

    # -- corpus encoding --------------------------------------------------
    def _encode_corpus(self, token_seqs: Iterable[List[str]]) -> List[np.ndarray]:
        enc = []
        for tokens in token_seqs:
            ids = [self.vocab.index_of(t) for t in tokens]
            ids = np.asarray([i for i in ids if i >= 0], dtype=np.int32)
            if ids.size:
                enc.append(ids)
        return enc

    def build_vocab(self, token_seqs: Iterable[List[str]]) -> None:
        self.vocab = VocabConstructor(
            self.min_word_frequency,
            special_tokens=self._special_tokens).build(token_seqs)
        if self.use_hs:
            build_huffman(self.vocab)
        self.lookup_table = InMemoryLookupTable(
            len(self.vocab), self.layer_size, seed=self.seed)
        self.lookup_table.reset_weights(self.use_hs, self.negative > 0)

    # -- pair generation (vectorized, host) -------------------------------
    def _sentence_pairs(self, ids: np.ndarray, rng: np.random.Generator,
                        keep: np.ndarray):
        """(centers, contexts) int32 arrays for one sentence: frequent-word
        subsampling then per-position reduced window b ~ U[1, window]."""
        if self.sampling > 0:
            ids = ids[rng.random(ids.size) < keep[ids]]
        n = ids.size
        if n < 2:
            return None
        W = self.window
        b = rng.integers(1, W + 1, size=n)
        offs = np.concatenate([np.arange(-W, 0), np.arange(1, W + 1)])
        pos = np.arange(n)[:, None] + offs[None, :]            # [n, 2W]
        valid = ((np.abs(offs)[None, :] <= b[:, None])
                 & (pos >= 0) & (pos < n))
        centers = np.broadcast_to(ids[:, None], valid.shape)[valid]
        contexts = ids[np.clip(pos, 0, n - 1)][valid]
        return centers, contexts

    def _sentence_windows(self, ids: np.ndarray, rng: np.random.Generator,
                          keep: np.ndarray):
        """CBOW grouping: (centers [n], contexts [n, 2W], ctx_mask [n, 2W])
        — the full reduced window per center position."""
        if self.sampling > 0:
            ids = ids[rng.random(ids.size) < keep[ids]]
        n = ids.size
        if n < 2:
            return None
        W = self.window
        b = rng.integers(1, W + 1, size=n)
        offs = np.concatenate([np.arange(-W, 0), np.arange(1, W + 1)])
        pos = np.arange(n)[:, None] + offs[None, :]
        valid = ((np.abs(offs)[None, :] <= b[:, None])
                 & (pos >= 0) & (pos < n))
        contexts = ids[np.clip(pos, 0, n - 1)] * valid
        return ids, contexts.astype(np.int32), valid.astype(np.float32)

    # -- device step ------------------------------------------------------
    # Max training rounds fused into one device dispatch. Through the TPU
    # relay a dispatch costs tens of ms regardless of payload, so the hot
    # loop runs a lax.scan over up to this many rounds per call (measured
    # ~3× throughput vs one-round-per-dispatch at B=8192).
    MAX_BLOCK_ROUNDS = 64

    def _make_block(self, hs_dev=None, cdf_dev=None):
        """Jitted (syn0, syn1, cols, key) -> (syn0', syn1', mean_loss)
        running a ``lax.scan`` of fused rounds; ``cols`` arrays carry a
        leading rounds axis and hold ONLY word indices + lr/mask — for HS
        configs each round gathers its Huffman paths from device-resident
        tables (``hs_dev``), for NS configs each round draws its negatives
        on device from the device-resident unigram CDF (``cdf_dev``) with
        jax threefry streams. The latter is a DOCUMENTED divergence from
        the reference's host-side PCG sampling (SURVEY declares statistical,
        not bitwise, RNG parity): it removes both the host sampling stage
        and 2/3 of the per-block host→device traffic."""
        import functools

        import jax
        import jax.numpy as jnp
        from jax import lax

        from ..ops import embeddings as E

        # Table-update lowering: MXU one-hot matmul for small vocabs,
        # scatter-add for large (see ops/embeddings.py module docstring).
        dense = len(self.vocab) <= E.DENSE_UPDATE_MAX_ROWS
        is_cbow = self.algorithm == "cbow"
        use_hs = self.use_hs
        V, K = len(self.vocab), self.negative
        if use_hs:
            points_d, codes_d, mask_d = hs_dev

        def draw_targets(key, pos):
            """[B, 1+K] device-sampled targets (col 0 = positive) +
            labels; collisions with the positive shifted by one (same
            shift the host path uses)."""
            negs = jnp.searchsorted(cdf_dev, jax.random.uniform(
                key, (pos.shape[0], K), dtype=cdf_dev.dtype))
            negs = jnp.where(negs == pos[:, None], (negs + 1) % V,
                             negs).astype(jnp.int32)
            tgt = jnp.concatenate([pos[:, None], negs], axis=1)
            lab = jnp.zeros(tgt.shape, jnp.float32).at[:, 0].set(1.0)
            return tgt, lab

        def body(carry, inp):
            s0, s1, key = carry
            key, sub = jax.random.split(key)
            if is_cbow and use_hs:
                ctx, cm, c, lr, pm = inp
                s0, s1, loss = E.cbow_hs(s0, s1, ctx, cm, points_d[c],
                                         codes_d[c], mask_d[c], lr, pm,
                                         dense=dense)
            elif is_cbow:
                ctx, cm, c, lr, pm = inp
                tgt, lab = draw_targets(sub, c)
                s0, s1, loss = E.cbow(s0, s1, ctx, cm, tgt, lab, lr, pm,
                                      dense=dense)
            elif use_hs:
                c, x, lr, pm = inp
                s0, s1, loss = E.skipgram_hs(s0, s1, c, points_d[x],
                                             codes_d[x], mask_d[x], lr, pm,
                                             dense=dense)
            else:
                c, x, lr, pm = inp
                tgt, lab = draw_targets(sub, x)
                s0, s1, loss = E.skipgram(s0, s1, c, tgt, lab, lr, pm,
                                          dense=dense)
            return (s0, s1, key), loss

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def block(syn0, syn1, cols, key):
            (syn0, syn1, _), losses = lax.scan(body, (syn0, syn1, key), cols)
            return syn0, syn1, losses.mean()

        return block

    @staticmethod
    def _pow2_floor(n: int) -> int:
        return 1 << (n.bit_length() - 1)

    def _train_encoded(self, corpus: List[np.ndarray],
                       stream_factory: Optional[Callable] = None,
                       total_words: Optional[int] = None) -> None:
        """Run the full fit over an encoded corpus.

        ``stream_factory(rng, keep)`` (optional) overrides per-sentence batch
        generation — it must yield ``(centers, contexts)`` tuples for
        skip-gram configs or ``(centers, ctx, cmask)`` for CBOW configs.
        ParagraphVectors uses this to inject doc-label ids into the stream.
        """
        import jax.numpy as jnp

        import jax

        rng = np.random.default_rng(self.seed)
        keep = subsample_keep_probs(self.vocab, self.sampling)
        hs_dev = cdf_dev = None
        if self.use_hs:
            hs_codes, hs_points, hs_mask = huffman_arrays(self.vocab)
            hs_dev = (jnp.asarray(hs_points), jnp.asarray(hs_codes),
                      jnp.asarray(hs_mask))
        else:
            cdf_dev = jnp.asarray(unigram_table(self.vocab),
                                  dtype=jnp.float32)
        block = self._make_block(hs_dev, cdf_dev)
        base_key = jax.random.PRNGKey(self.seed)
        n_blocks = 0
        V = len(self.vocab)
        B, K = self.batch_size, self.negative
        if total_words is None:
            total_words = (sum(len(s) for s in corpus)
                           * self.epochs * self.iterations)
        syn0 = jnp.asarray(self.lookup_table.syn0)
        syn1 = jnp.asarray(self.lookup_table.syn1 if self.use_hs
                           else self.lookup_table.syn1neg)

        is_cbow = self.algorithm == "cbow"
        words_seen = 0     # corpus words consumed (drives the LR schedule)
        pairs_seen = 0     # training examples executed on device
        losses = []
        t0 = time.perf_counter()

        def _lr() -> np.float32:
            # Linear decay by CORPUS WORDS CONSUMED (word2vec.c semantics:
            # alpha decays with corpus progress, not with pair count).
            frac = min(words_seen / max(total_words, 1), 1.0)
            return np.float32(max(self.learning_rate * (1 - frac),
                                  self.min_learning_rate))

        def _rounds(npairs):
            """Pad-to-B bookkeeping shared by both flushes."""
            pad = (-npairs) % B
            pm = np.ones(npairs + pad, dtype=np.float32)
            pm[npairs:] = 0.0
            return pad, pm, (npairs + pad) // B

        def _dispatch(cols_fn, R):
            """Run R rounds as pow2-sized scanned blocks (bounded set of
            compiled shapes)."""
            nonlocal syn0, syn1, n_blocks
            r = 0
            while r < R:
                nb = min(self.MAX_BLOCK_ROUNDS, self._pow2_floor(R - r))
                key = jax.random.fold_in(base_key, n_blocks)
                n_blocks += 1
                syn0, syn1, loss = block(syn0, syn1, cols_fn(r, nb), key)
                losses.append(loss)   # device scalar; no sync in the loop
                r += nb

        def flush_sg(centers, contexts):
            nonlocal pairs_seen
            npairs = centers.size
            pad, pm, R = _rounds(npairs)
            c3 = np.pad(centers, (0, pad)).reshape(R, B)
            x3 = np.pad(contexts, (0, pad)).reshape(R, B)
            pm3 = pm.reshape(R, B)
            lr = _lr()

            def cols_fn(r, nb):
                sl = slice(r, r + nb)
                return (c3[sl], x3[sl], np.full(nb, lr, np.float32), pm3[sl])

            _dispatch(cols_fn, R)
            pairs_seen += npairs

        def flush_cbow(centers, ctx, cmask):
            nonlocal pairs_seen
            npairs = centers.size
            pad, pm, R = _rounds(npairs)
            W = ctx.shape[1]
            c3 = np.pad(centers, (0, pad)).reshape(R, B)
            ctx3 = np.pad(ctx, ((0, pad), (0, 0))).reshape(R, B, W)
            cm3 = np.pad(cmask, ((0, pad), (0, 0))).reshape(R, B, W)
            pm3 = pm.reshape(R, B)
            lr = _lr()

            def cols_fn(r, nb):
                sl = slice(r, r + nb)
                return (ctx3[sl], cm3[sl], c3[sl],
                        np.full(nb, lr, np.float32), pm3[sl])

            _dispatch(cols_fn, R)
            pairs_seen += npairs

        def default_stream(rng, keep):
            if is_cbow:
                for ids in corpus:
                    wins = self._sentence_windows(ids, rng, keep)
                    if wins is not None:
                        yield (ids.size,) + wins
                return
            # skip-gram pair generation: one native call per sentence chunk
            # (libdatavec_native, SURVEY §7.1.2 "native where the reference
            # is native") with the numpy per-sentence path as fallback
            from .. import native

            if native.available():
                CHUNK = 2048
                keep_arr = keep if self.sampling > 0 else None
                for s0 in range(0, len(corpus), CHUNK):
                    chunk = corpus[s0:s0 + CHUNK]
                    offsets = np.zeros(len(chunk) + 1, np.int64)
                    np.cumsum([c.size for c in chunk], out=offsets[1:])
                    flat = np.concatenate(chunk) if chunk else \
                        np.empty(0, np.int32)
                    c, x = native.sg_pairs(
                        flat, offsets, self.window, keep_arr,
                        int(rng.integers(1, 2 ** 63 - 1)))
                    if c.size:
                        yield int(offsets[-1]), c, x
                return
            for ids in corpus:
                pairs = self._sentence_pairs(ids, rng, keep)
                if pairs is not None:
                    yield (ids.size,) + pairs

        if stream_factory is None:
            stream_factory = default_stream

        for _epoch in range(self.epochs):
            if is_cbow:
                buf = []
                buffered = 0
                for item in stream_factory(rng, keep):
                    nwords, wins = item[0], item[1:]
                    words_seen += nwords * self.iterations
                    for _ in range(self.iterations):
                        buf.append(wins)
                        buffered += wins[0].size
                    if buffered >= 64 * B:
                        flush_cbow(np.concatenate([w[0] for w in buf]),
                                   np.concatenate([w[1] for w in buf]),
                                   np.concatenate([w[2] for w in buf]))
                        buf, buffered = [], 0
                if buf:
                    flush_cbow(np.concatenate([w[0] for w in buf]),
                               np.concatenate([w[1] for w in buf]),
                               np.concatenate([w[2] for w in buf]))
            else:
                buf_c: List[np.ndarray] = []
                buf_x: List[np.ndarray] = []
                buffered = 0
                for item in stream_factory(rng, keep):
                    nwords, pairs = item[0], item[1:]
                    words_seen += nwords * self.iterations
                    for _ in range(self.iterations):
                        buf_c.append(pairs[0])
                        buf_x.append(pairs[1])
                        buffered += pairs[0].size
                    if buffered >= 64 * B:
                        flush_sg(np.concatenate(buf_c), np.concatenate(buf_x))
                        buf_c, buf_x, buffered = [], [], 0
                if buffered:
                    flush_sg(np.concatenate(buf_c), np.concatenate(buf_x))

        syn0.block_until_ready()
        dt = time.perf_counter() - t0
        self.words_per_sec = words_seen / max(dt, 1e-9)
        self.pairs_per_sec = pairs_seen / max(dt, 1e-9)
        self.last_loss = float(np.mean([float(l) for l in losses[-50:]])) \
            if losses else 0.0
        self.lookup_table.syn0 = np.asarray(syn0)
        if self.use_hs:
            self.lookup_table.syn1 = np.asarray(syn1)
        else:
            self.lookup_table.syn1neg = np.asarray(syn1)

    @staticmethod
    def _neg_targets(pos: np.ndarray, rng: np.random.Generator,
                     cdf: np.ndarray, V: int, K: int):
        """[B, 1+K] targets (col 0 = positive) + labels; negatives drawn
        from the unigram^0.75 CDF, collisions with the positive shifted by
        one (the reference resamples; a deterministic shift is unbiased to
        O(1/V) and keeps the host path branch-free)."""
        B = pos.shape[0]
        negs = np.searchsorted(cdf, rng.random((B, K))).astype(np.int32)
        negs = np.where(negs == pos[:, None], (negs + 1) % V, negs)
        targets = np.concatenate([pos[:, None], negs], axis=1)
        labels = np.zeros((B, 1 + K), dtype=np.float32)
        labels[:, 0] = 1.0
        return targets, labels


class Word2Vec(SequenceVectors):
    """Word2Vec over a sentence corpus (reference: Word2Vec.Builder →
    SequenceVectors.fit, SURVEY §3.6)."""

    class Builder:
        def __init__(self) -> None:
            self._kw = {}
            self._iter: Optional[SentenceIterator] = None
            self._tok: TokenizerFactory = DefaultTokenizerFactory()

        def min_word_frequency(self, v): self._kw["min_word_frequency"] = v; return self
        def iterations(self, v): self._kw["iterations"] = v; return self
        def epochs(self, v): self._kw["epochs"] = v; return self
        def layer_size(self, v): self._kw["layer_size"] = v; return self
        def seed(self, v): self._kw["seed"] = v; return self
        def window_size(self, v): self._kw["window"] = v; return self
        def learning_rate(self, v): self._kw["learning_rate"] = v; return self
        def min_learning_rate(self, v): self._kw["min_learning_rate"] = v; return self
        def negative_sample(self, v): self._kw["negative"] = int(v); return self
        def use_hierarchic_softmax(self, v): self._kw["use_hierarchic_softmax"] = v; return self
        def sampling(self, v): self._kw["sampling"] = v; return self
        def batch_size(self, v): self._kw["batch_size"] = v; return self
        def workers(self, v): self._kw["workers"] = v; return self

        def elements_learning_algorithm(self, name: str):
            self._kw["algorithm"] = \
                "cbow" if "cbow" in name.lower() else "skipgram"
            return self

        def iterate(self, it: SentenceIterator):
            self._iter = it
            return self

        def tokenizer_factory(self, tf: TokenizerFactory):
            self._tok = tf
            return self

        def build(self) -> "Word2Vec":
            w2v = Word2Vec(**self._kw)
            w2v._sentence_iter = self._iter
            w2v._tokenizer = self._tok
            return w2v

    @staticmethod
    def builder() -> "Word2Vec.Builder":
        return Word2Vec.Builder()

    def __init__(self, **kw):
        super().__init__(**kw)
        self._sentence_iter: Optional[SentenceIterator] = None
        self._tokenizer: TokenizerFactory = DefaultTokenizerFactory()

    def set_sentence_iterator(self, it) -> None:
        if isinstance(it, (list, tuple)):
            it = CollectionSentenceIterator(it)
        self._sentence_iter = it

    def _token_stream(self):
        assert self._sentence_iter is not None, \
            "no corpus: call iterate()/set_sentence_iterator first"
        self._sentence_iter.reset()
        for sentence in self._sentence_iter:
            yield self._tokenizer.create(sentence).get_tokens()

    def fit(self) -> None:
        """Train. First call builds the vocab and initializes tables; a
        model that already has vocab + tables (a second ``fit`` or one
        restored by ``read_word2vec_model``) RESUMES training with the
        existing state — corpus words outside the stored vocab are
        dropped."""
        if len(self.vocab) == 0 or self.lookup_table.syn0 is None:
            self.build_vocab(self._token_stream())
            if len(self.vocab) == 0:
                raise ValueError("empty vocabulary after pruning — lower "
                                 "min_word_frequency or supply more text")
        corpus = self._encode_corpus(self._token_stream())
        self._train_encoded(corpus)
