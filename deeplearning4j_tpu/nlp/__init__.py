"""NLP stack: Word2Vec family (reference: deeplearning4j-nlp, SURVEY §2.3/§3.6).

- ``text``               tokenizers + sentence iterators (TokenizerFactory SPI)
- ``vocab``              VocabCache/VocabConstructor, Huffman, unigram table
- ``lookup_table``       InMemoryLookupTable (syn0/syn1/syn1neg)
- ``word2vec``           SequenceVectors engine + Word2Vec builder front
- ``paragraph_vectors``  ParagraphVectors: PV-DM / PV-DBOW + infer_vector
- ``glove``              Glove: co-occurrence counting + AdaGrad factorization
- ``fasttext``           FastText: subword (char n-gram) vectors, OOV queries
- ``graph_vectors``      DeepWalk / Node2Vec over random walks
- ``serializer``         WordVectorSerializer: txt / Google-bin / model zip

The fused skip-gram/CBOW device rounds live in ``ops/embeddings.py`` (the
TPU analog of libnd4j's sg_cb kernels).
"""

from .fasttext import FastText, char_ngrams, fasttext_hash
from .glove import Glove
from .graph_vectors import DeepWalk, Graph, Node2Vec, random_walks
from .lookup_table import InMemoryLookupTable
from .paragraph_vectors import ParagraphVectors
from .serializer import (read_paragraph_vectors, read_word2vec_model,
                         read_word_vectors, write_paragraph_vectors,
                         write_word2vec_model, write_word_vectors)
from .text import (CollectionSentenceIterator, CommonPreprocessor,
                   DefaultTokenizerFactory, FileSentenceIterator,
                   LabelAwareIterator, LineSentenceIterator,
                   NGramTokenizerFactory, SentenceIterator, Tokenizer,
                   TokenizerFactory)
from .vocab import (VocabCache, VocabConstructor, VocabWord, build_huffman,
                    huffman_arrays, subsample_keep_probs, unigram_table)
from .word2vec import SequenceVectors, Word2Vec, WordVectors

__all__ = [
    "CollectionSentenceIterator", "CommonPreprocessor", "DeepWalk",
    "DefaultTokenizerFactory", "FastText", "FileSentenceIterator", "Glove",
    "Graph", "InMemoryLookupTable", "Node2Vec", "char_ngrams",
    "fasttext_hash", "random_walks",
    "LabelAwareIterator", "LineSentenceIterator", "NGramTokenizerFactory",
    "ParagraphVectors", "SentenceIterator", "SequenceVectors", "Tokenizer",
    "TokenizerFactory", "VocabCache", "VocabConstructor", "VocabWord",
    "Word2Vec", "WordVectors", "build_huffman", "huffman_arrays",
    "read_word2vec_model", "read_word_vectors", "subsample_keep_probs",
    "unigram_table", "write_word2vec_model", "write_word_vectors",
    "write_paragraph_vectors", "read_paragraph_vectors",
]
