"""StatsListener → StatsStorage: the training metrics bus.

Reference: deeplearning4j-ui ``org.deeplearning4j.ui.model.stats.StatsListener``
→ ``StatsStorage`` (InMemoryStatsStorage / FileStatsStorage) → Play UI
(SURVEY.md §2.3 Training UI row, §5.5). The reference streams score, update:
parameter ratios, per-layer param/gradient/update histograms, memory and
timing into a storage SPI the UI polls.

TPU shape: the listener receives the DEVICE loss scalar from the fit loop
(multilayer.py contract — listeners must not force a per-iteration sync) and
reads it back only every ``collect_every_n`` iterations, batching one device
sync with the (host-side) param-norm computation. Storage backends:
in-memory (queryable), JSONL file, and TensorBoard event files — the
dashboard story is "point TensorBoard at the logdir" instead of the
reference's bundled Play webserver.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..optimize.listeners import TrainingListener
from .tensorboard import TensorBoardEventWriter, host_histogram


class StatsStorage:
    """SPI (reference: StatsStorage / StatsStorageRouter)."""

    def put_scalar(self, session: str, tag: str, step: int,
                   value: float) -> None:
        raise NotImplementedError

    def put_histogram(self, session: str, tag: str, step: int,
                      values) -> None:
        """Histogram record (reference StatsListener's per-layer param/
        gradient/update histograms). Default: dropped — scalar-only
        backends stay valid without knowing about histograms."""

    def close(self) -> None:
        pass


class InMemoryStatsStorage(StatsStorage):
    def __init__(self) -> None:
        self.records: List[Dict[str, Any]] = []
        self.histograms: List[Dict[str, Any]] = []

    def put_scalar(self, session, tag, step, value):
        self.records.append({"session": session, "tag": tag, "step": step,
                             "value": float(value), "time": time.time()})

    def put_histogram(self, session, tag, step, values):
        _, counts, edges = host_histogram(values)
        self.histograms.append({
            "session": session, "tag": tag, "step": step,
            "bucket": counts.tolist(), "bucket_limit": edges[1:].tolist(),
            "time": time.time()})

    # -- queries (reference: StatsStorage.getAllUpdatesAfter etc.) -------
    def tags(self) -> List[str]:
        return sorted({r["tag"] for r in self.records})

    def histogram_tags(self) -> List[str]:
        return sorted({r["tag"] for r in self.histograms})

    def series(self, tag: str) -> List[tuple]:
        return [(r["step"], r["value"]) for r in self.records
                if r["tag"] == tag]


class FileStatsStorage(StatsStorage):
    """Append-only JSONL (reference: FileStatsStorage's MapDB file)."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "a")

    def put_scalar(self, session, tag, step, value):
        self._f.write(json.dumps({"session": session, "tag": tag,
                                  "step": step, "value": float(value),
                                  "time": time.time()}) + "\n")
        # per-write flush: a live dashboard (UIServer) tails this file
        # per request, and buffered records would lag it by ~8 KB
        self._f.flush()

    def put_histogram(self, session, tag, step, values):
        _, counts, edges = host_histogram(values)
        # "kind" distinguishes the record; scalar consumers (UIServer
        # series) filter on the presence of "value"
        self._f.write(json.dumps({"kind": "histogram", "session": session,
                                  "tag": tag, "step": step,
                                  "bucket": counts.tolist(),
                                  "bucket_limit": edges[1:].tolist(),
                                  "time": time.time()}) + "\n")
        self._f.flush()

    def close(self):
        self._f.close()

    @staticmethod
    def read(path: str) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        with open(path) as f:
            for line in f:
                if not line.strip():
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    # torn tail line of a file being written concurrently
                    continue
        return out


class TensorBoardStatsStorage(StatsStorage):
    """Scalars as TensorBoard events — `tensorboard --logdir` IS the
    training UI (SURVEY §5.5's named equivalent)."""

    def __init__(self, logdir: str):
        self._writer = TensorBoardEventWriter(logdir)

    def put_scalar(self, session, tag, step, value):
        self._writer.add_scalar(f"{session}/{tag}" if session else tag,
                                value, step)
        self._writer.flush()

    def put_histogram(self, session, tag, step, values):
        self._writer.add_histogram(f"{session}/{tag}" if session else tag,
                                   values, step)
        self._writer.flush()

    def close(self):
        self._writer.close()


class StatsListener(TrainingListener):
    """Collect score + per-layer parameter/update statistics every N
    iterations into a StatsStorage (reference: StatsListener with its
    reportingFrequency)."""

    def __init__(self, storage: StatsStorage, collect_every_n: int = 10,
                 session_id: str = "", collect_param_norms: bool = True,
                 collect_timing: bool = True,
                 collect_histograms: bool = False):
        self.storage = storage
        self.every = max(1, collect_every_n)
        self.session = session_id
        self.collect_param_norms = collect_param_norms
        self.collect_timing = collect_timing
        self.collect_histograms = collect_histograms
        self._last_time: Optional[float] = None

    def iteration_done(self, model, iteration: int, score) -> None:
        if iteration % self.every:
            return
        # ONE device sync per collection window, not per iteration
        self.storage.put_scalar(self.session, "score", iteration,
                                float(score))
        if self.collect_timing:
            now = time.perf_counter()
            if self._last_time is not None:
                per_iter = (now - self._last_time) / self.every
                self.storage.put_scalar(self.session, "iteration_ms",
                                        iteration, per_iter * 1e3)
            self._last_time = now
        if self.collect_param_norms or self.collect_histograms:
            params = getattr(model, "_params", None)
            # MultiLayerNetwork keeps a per-layer param list; SameDiff's
            # _params is a METHOD returning {name: array} — support both
            if callable(params):
                params = [params()]
            if not isinstance(params, (list, tuple)):
                params = []
            if params:
                import jax

                # ONE batched transfer of the whole param tree — a
                # per-array np.asarray loop would pay one device sync per
                # parameter and defeat the "one sync per collection
                # window" contract this listener advertises
                params = jax.device_get(params)
            for i, lp in enumerate(params):
                for name, w in lp.items():
                    # np.array, not np.asarray: on the CPU backend the
                    # batched device_get above returns zero-copy views of
                    # donatable buffers, and put_histogram STORES the
                    # array — it must own its bytes
                    arr = np.array(w)
                    if self.collect_param_norms:
                        self.storage.put_scalar(
                            self.session, f"param_mean_magnitude/{i}_{name}",
                            iteration, float(np.mean(np.abs(arr))))
                    if self.collect_histograms:
                        self.storage.put_histogram(
                            self.session, f"param/{i}_{name}", iteration,
                            arr)

    def epoch_done(self, model, epoch: int) -> None:
        self.storage.put_scalar(self.session, "epoch", epoch, epoch)
