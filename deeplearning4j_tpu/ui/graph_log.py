"""SameDiff graph-structure log + UI rendering data.

Reference: nd4j ``org/nd4j/graph/ui/LogFileWriter`` writing the
``uigraphstatic.fbs`` FlatBuffers event log that the Vertx UI renders as
its "SameDiff" tab (SURVEY §5.5). TPU-native shape: the static graph
structure serializes as one JSON document (ops, variables, edges,
topological depth for layout); the dashboard serves it at ``/api/graph``
and renders a layered node list. Scalar EVENTS keep riding the existing
stats bus — this log is the STATIC half, like the reference's
``writeGraphStructure``.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional


def graph_structure(sd) -> Dict[str, Any]:
    """Extract the renderable structure of a SameDiff graph: variables
    (with type/shape/dtype), ops (with inputs/outputs), and a layered
    topological depth per op for drawing."""
    vars_out: List[Dict[str, Any]] = []
    for name, v in sd._vars.items():
        vars_out.append({
            "name": name,
            "type": str(getattr(v.vtype, "name", v.vtype)),
            "shape": (list(v.shape) if v.shape is not None else None),
            "dtype": str(v.dtype) if getattr(v, "dtype", None) else None,
        })
    depth: Dict[str, int] = {}
    ops_out: List[Dict[str, Any]] = []
    for node in sd._nodes:
        d = 1 + max((depth.get(i, 0) for i in node.inputs), default=0)
        for o in node.outputs:
            depth[o] = d
        ops_out.append({
            "name": node.outputs[0] if node.outputs else f"op{node.id}",
            "op": node.op_name,
            "inputs": list(node.inputs),
            "outputs": list(node.outputs),
            "depth": d,
        })
    return {
        "variables": vars_out,
        "ops": ops_out,
        "placeholders": list(sd.placeholders()),
        "n_ops": len(ops_out),
        "n_vars": len(vars_out),
        "max_depth": max(depth.values(), default=0),
    }


class LogFileWriter:
    """Reference-shaped writer: ``write_graph_structure(sd)`` appends one
    static-structure record; ``write_scalar_event`` appends events (the
    dynamic half) — both as JSON lines so the file tails cleanly."""

    def __init__(self, path: str):
        self.path = str(path)
        self._f = open(self.path, "a", encoding="utf-8")

    def write_graph_structure(self, sd) -> None:
        rec = {"type": "graph", **graph_structure(sd)}
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()

    # reference spelling
    writeGraphStructure = write_graph_structure

    def write_scalar_event(self, name: str, step: int,
                           value: float) -> None:
        self._f.write(json.dumps({"type": "event", "name": name,
                                  "step": int(step),
                                  "value": float(value)}) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_graph_log(path: str) -> Dict[str, Any]:
    """Last graph record + all events from a log file (torn trailing
    lines skipped, like FileStatsStorage)."""
    graph: Optional[Dict[str, Any]] = None
    events: List[Dict[str, Any]] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("type") == "graph":
                    graph = rec
                elif rec.get("type") == "event":
                    events.append(rec)
    except OSError:
        pass
    return {"graph": graph, "events": events}
