"""Training UI / metrics bus (reference: deeplearning4j-ui, SURVEY §5.5).

The reference ships a Play webserver fed by StatsListener→StatsStorage; the
TPU stack's dashboard is TensorBoard — ``StatsListener`` routes the same
metrics into event files (``TensorBoardStatsStorage``), an in-memory store
for programmatic queries, or JSONL. Device-side kernel traces come from
``common.profiler.OpProfiler`` (jax.profiler → TensorBoard trace viewer).
"""

from .stats import (FileStatsStorage, InMemoryStatsStorage, StatsListener,
                    StatsStorage, TensorBoardStatsStorage)
from .tensorboard import (TensorBoardEventWriter, read_histogram_events,
                          read_scalar_events)
from .server import RemoteUIStatsStorageRouter, UIServer
# the device half of the metrics bus (in-graph telemetry) lives in
# optimize.telemetry; re-exported here so the three-line attach
# (listener -> storage -> TensorBoard/UIServer) is one import
from ..optimize.telemetry import NanSentinelListener, TelemetrySink

__all__ = [
    "FileStatsStorage", "InMemoryStatsStorage", "StatsListener",
    "StatsStorage", "TensorBoardStatsStorage", "TensorBoardEventWriter",
    "read_scalar_events", "read_histogram_events", "UIServer",
    "RemoteUIStatsStorageRouter", "TelemetrySink", "NanSentinelListener",
]
