"""TensorBoard event-file writer — no tensorflow import required.

SURVEY §5.5 names TensorBoard events as the TPU-stack equivalent of the
reference's Training UI wire (StatsListener → StatsStorage → Play UI). This
module writes scalar and histogram summaries in the standard ``tfevents``
TFRecord format (public, stable format: length-prefixed records with masked
CRC32C, protobuf ``Event``/``Summary``/``HistogramProto`` payloads
hand-encoded below — only a handful of fields are needed, so a protobuf
dependency would be overkill and a tensorflow import costs ~10 s of
startup).
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import Optional

import numpy as np

# --- CRC32C (Castagnoli), table-driven --------------------------------------

def _build_crc_table():
    poly = 0x82F63B78
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        table.append(crc)
    return table


# built eagerly at import: a lazy build racing across writer threads could
# interleave appends and corrupt every CRC for the process lifetime
_CRC_TABLE = _build_crc_table()


def _crc32c(data: bytes) -> int:
    table = _CRC_TABLE
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# --- minimal protobuf encoding ----------------------------------------------

def _varint(n: int) -> bytes:
    if n < 0:
        raise ValueError(f"protobuf varint fields here are unsigned; got {n}")
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field_bytes(num: int, payload: bytes) -> bytes:
    return _varint((num << 3) | 2) + _varint(len(payload)) + payload


def _field_double(num: int, v: float) -> bytes:
    return _varint((num << 3) | 1) + struct.pack("<d", v)


def _field_float(num: int, v: float) -> bytes:
    return _varint((num << 3) | 5) + struct.pack("<f", v)


def _field_varint(num: int, v: int) -> bytes:
    return _varint(num << 3) + _varint(v)


def _event(wall_time: float, step: Optional[int] = None,
           file_version: Optional[str] = None,
           summary: Optional[bytes] = None) -> bytes:
    out = _field_double(1, wall_time)
    if step is not None:
        out += _field_varint(2, step)
    if file_version is not None:
        out += _field_bytes(3, file_version.encode())
    if summary is not None:
        out += _field_bytes(5, summary)
    return out


def _scalar_summary(tag: str, value: float) -> bytes:
    val = _field_bytes(1, tag.encode()) + _field_float(2, float(value))
    return _field_bytes(1, val)


def _packed_doubles(num: int, values) -> bytes:
    """Packed repeated double field (HistogramProto bucket/bucket_limit)."""
    payload = b"".join(struct.pack("<d", float(v)) for v in values)
    return _field_bytes(num, payload)


def host_histogram(values, bins: int = 30):
    """(finite_values, counts, edges) — the one histogram-preparation
    convention shared by every storage backend: non-finite values are
    dropped (TensorBoard refuses NaN bucket stats, np.histogram's
    auto-range refuses NaN) and an all-empty input degrades to a single
    zero bucket."""
    v = np.asarray(values, np.float64).ravel()
    v = v[np.isfinite(v)]
    if v.size == 0:
        v = np.zeros((1,))
    counts, edges = np.histogram(v, bins=bins)
    return v, counts, edges


def _histogram_summary(tag: str, values, bins: int = 30) -> bytes:
    """Summary.Value with a ``histo`` (HistogramProto, field 5) payload."""
    v, counts, edges = host_histogram(values, bins)
    histo = (_field_double(1, float(v.min()))          # min
             + _field_double(2, float(v.max()))        # max
             + _field_double(3, float(v.size))         # num
             + _field_double(4, float(v.sum()))        # sum
             + _field_double(5, float(np.square(v).sum()))  # sum_squares
             + _packed_doubles(6, edges[1:])           # bucket right edges
             + _packed_doubles(7, counts))             # bucket counts
    val = _field_bytes(1, tag.encode()) + _field_bytes(5, histo)
    return _field_bytes(1, val)


class TensorBoardEventWriter:
    """Append scalar events to a ``tfevents`` file under ``logdir``
    (one file per writer, standard naming so TensorBoard discovers it)."""

    def __init__(self, logdir: str):
        os.makedirs(logdir, exist_ok=True)
        fname = (f"events.out.tfevents.{int(time.time())}."
                 f"{socket.gethostname()}")
        self.path = os.path.join(logdir, fname)
        self._f = open(self.path, "ab")
        self._write_record(_event(time.time(),
                                  file_version="brain.Event:2"))

    def _write_record(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        self._write_record(_event(time.time(), step=step,
                                  summary=_scalar_summary(tag, value)))

    def add_histogram(self, tag: str, values, step: int,
                      bins: int = 30) -> None:
        """Histogram summary (reference StatsListener's per-layer param/
        gradient/update histograms land here; TensorBoard's Histograms/
        Distributions tabs render them)."""
        self._write_record(_event(time.time(), step=step,
                                  summary=_histogram_summary(tag, values,
                                                             bins)))

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self.flush()
        self._f.close()


def _iter_record_payloads(path: str):
    """Yield the event payloads of a tfevents file, verifying the TFRecord
    framing (header + payload masked CRC32C)."""
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                break
            (length,) = struct.unpack("<Q", header)
            (hcrc,) = struct.unpack("<I", f.read(4))
            if hcrc != _masked_crc(header):
                raise ValueError("corrupt header CRC")
            payload = f.read(length)
            (pcrc,) = struct.unpack("<I", f.read(4))
            if pcrc != _masked_crc(payload):
                raise ValueError("corrupt payload CRC")
            yield payload


def read_scalar_events(path: str):
    """Parse a tfevents file back into [(step, tag, value)] — used by tests
    to prove the files are well-formed (record framing + CRCs verified)."""
    out = []
    for payload in _iter_record_payloads(path):
        out.extend((s, t, v) for s, t, v, h in _parse_event(payload)
                   if h is None)
    return out


def read_histogram_events(path: str):
    """Parse a tfevents file's histogram summaries into
    [(step, tag, histo)] with ``histo`` a dict of the HistogramProto
    fields (min/max/num/sum/sum_squares/bucket_limit/bucket)."""
    out = []
    for payload in _iter_record_payloads(path):
        out.extend((s, t, h) for s, t, _v, h in _parse_event(payload)
                   if h is not None)
    return out


def _read_varint(buf: bytes, i: int):
    shift = n = 0
    while True:
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7


def _parse_event(buf: bytes):
    i = 0
    step = 0
    values = []
    while i < len(buf):
        key, i = _read_varint(buf, i)
        num, wire = key >> 3, key & 7
        if wire == 1:
            i += 8
        elif wire == 5:
            i += 4
        elif wire == 0:
            v, i = _read_varint(buf, i)
            if num == 2:
                step = v
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            chunk = buf[i:i + ln]
            i += ln
            if num == 5:  # summary
                values.extend(_parse_summary(chunk))
    return [(step, tag, val, histo) for tag, val, histo in values]


def _parse_summary(buf: bytes):
    i = 0
    out = []
    while i < len(buf):
        key, i = _read_varint(buf, i)
        num, wire = key >> 3, key & 7
        if wire == 2:
            ln, i = _read_varint(buf, i)
            if num == 1:  # Value
                out.append(_parse_value(buf[i:i + ln]))
            i += ln
        elif wire == 5:
            i += 4
        elif wire == 1:
            i += 8
        else:
            _, i = _read_varint(buf, i)
    return out


def _parse_value(buf: bytes):
    i = 0
    tag, val, histo = "", float("nan"), None
    while i < len(buf):
        key, i = _read_varint(buf, i)
        num, wire = key >> 3, key & 7
        if wire == 2:
            ln, i = _read_varint(buf, i)
            if num == 1:
                tag = buf[i:i + ln].decode()
            elif num == 5:  # histo (HistogramProto)
                histo = _parse_histo(buf[i:i + ln])
            i += ln
        elif wire == 5:
            if num == 2:
                (val,) = struct.unpack("<f", buf[i:i + 4])
            i += 4
        elif wire == 1:
            i += 8
        else:
            _, i = _read_varint(buf, i)
    return tag, val, histo


_HISTO_DOUBLES = {1: "min", 2: "max", 3: "num", 4: "sum", 5: "sum_squares"}


def _parse_histo(buf: bytes):
    out = {"min": 0.0, "max": 0.0, "num": 0.0, "sum": 0.0,
           "sum_squares": 0.0, "bucket_limit": [], "bucket": []}
    i = 0
    while i < len(buf):
        key, i = _read_varint(buf, i)
        num, wire = key >> 3, key & 7
        if wire == 1:
            (v,) = struct.unpack("<d", buf[i:i + 8])
            i += 8
            if num in _HISTO_DOUBLES:
                out[_HISTO_DOUBLES[num]] = v
        elif wire == 2:  # packed repeated double
            ln, i = _read_varint(buf, i)
            chunk = buf[i:i + ln]
            i += ln
            vals = [struct.unpack("<d", chunk[k:k + 8])[0]
                    for k in range(0, len(chunk) - 7, 8)]
            if num == 6:
                out["bucket_limit"] = vals
            elif num == 7:
                out["bucket"] = vals
        elif wire == 5:
            i += 4
        else:
            _, i = _read_varint(buf, i)
    return out
