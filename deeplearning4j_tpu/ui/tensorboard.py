"""TensorBoard event-file writer — no tensorflow import required.

SURVEY §5.5 names TensorBoard events as the TPU-stack equivalent of the
reference's Training UI wire (StatsListener → StatsStorage → Play UI). This
module writes scalar summaries in the standard ``tfevents`` TFRecord format
(public, stable format: length-prefixed records with masked CRC32C, protobuf
``Event``/``Summary`` payloads hand-encoded below — only the three scalar
fields are needed, so a protobuf dependency would be overkill and a
tensorflow import costs ~10 s of startup).
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import Optional

# --- CRC32C (Castagnoli), table-driven --------------------------------------

def _build_crc_table():
    poly = 0x82F63B78
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        table.append(crc)
    return table


# built eagerly at import: a lazy build racing across writer threads could
# interleave appends and corrupt every CRC for the process lifetime
_CRC_TABLE = _build_crc_table()


def _crc32c(data: bytes) -> int:
    table = _CRC_TABLE
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# --- minimal protobuf encoding ----------------------------------------------

def _varint(n: int) -> bytes:
    if n < 0:
        raise ValueError(f"protobuf varint fields here are unsigned; got {n}")
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field_bytes(num: int, payload: bytes) -> bytes:
    return _varint((num << 3) | 2) + _varint(len(payload)) + payload


def _field_double(num: int, v: float) -> bytes:
    return _varint((num << 3) | 1) + struct.pack("<d", v)


def _field_float(num: int, v: float) -> bytes:
    return _varint((num << 3) | 5) + struct.pack("<f", v)


def _field_varint(num: int, v: int) -> bytes:
    return _varint(num << 3) + _varint(v)


def _event(wall_time: float, step: Optional[int] = None,
           file_version: Optional[str] = None,
           summary: Optional[bytes] = None) -> bytes:
    out = _field_double(1, wall_time)
    if step is not None:
        out += _field_varint(2, step)
    if file_version is not None:
        out += _field_bytes(3, file_version.encode())
    if summary is not None:
        out += _field_bytes(5, summary)
    return out


def _scalar_summary(tag: str, value: float) -> bytes:
    val = _field_bytes(1, tag.encode()) + _field_float(2, float(value))
    return _field_bytes(1, val)


class TensorBoardEventWriter:
    """Append scalar events to a ``tfevents`` file under ``logdir``
    (one file per writer, standard naming so TensorBoard discovers it)."""

    def __init__(self, logdir: str):
        os.makedirs(logdir, exist_ok=True)
        fname = (f"events.out.tfevents.{int(time.time())}."
                 f"{socket.gethostname()}")
        self.path = os.path.join(logdir, fname)
        self._f = open(self.path, "ab")
        self._write_record(_event(time.time(),
                                  file_version="brain.Event:2"))

    def _write_record(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        self._write_record(_event(time.time(), step=step,
                                  summary=_scalar_summary(tag, value)))

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self.flush()
        self._f.close()


def read_scalar_events(path: str):
    """Parse a tfevents file back into [(step, tag, value)] — used by tests
    to prove the files are well-formed (record framing + CRCs verified)."""
    out = []
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                break
            (length,) = struct.unpack("<Q", header)
            (hcrc,) = struct.unpack("<I", f.read(4))
            if hcrc != _masked_crc(header):
                raise ValueError("corrupt header CRC")
            payload = f.read(length)
            (pcrc,) = struct.unpack("<I", f.read(4))
            if pcrc != _masked_crc(payload):
                raise ValueError("corrupt payload CRC")
            out.extend(_parse_event(payload))
    return out


def _read_varint(buf: bytes, i: int):
    shift = n = 0
    while True:
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7


def _parse_event(buf: bytes):
    i = 0
    step = 0
    values = []
    while i < len(buf):
        key, i = _read_varint(buf, i)
        num, wire = key >> 3, key & 7
        if wire == 1:
            i += 8
        elif wire == 5:
            i += 4
        elif wire == 0:
            v, i = _read_varint(buf, i)
            if num == 2:
                step = v
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            chunk = buf[i:i + ln]
            i += ln
            if num == 5:  # summary
                values.extend(_parse_summary(chunk))
    return [(step, tag, val) for tag, val in values]


def _parse_summary(buf: bytes):
    i = 0
    out = []
    while i < len(buf):
        key, i = _read_varint(buf, i)
        num, wire = key >> 3, key & 7
        if wire == 2:
            ln, i = _read_varint(buf, i)
            if num == 1:  # Value
                out.append(_parse_value(buf[i:i + ln]))
            i += ln
        elif wire == 5:
            i += 4
        elif wire == 1:
            i += 8
        else:
            _, i = _read_varint(buf, i)
    return out


def _parse_value(buf: bytes):
    i = 0
    tag, val = "", float("nan")
    while i < len(buf):
        key, i = _read_varint(buf, i)
        num, wire = key >> 3, key & 7
        if wire == 2:
            ln, i = _read_varint(buf, i)
            if num == 1:
                tag = buf[i:i + ln].decode()
            i += ln
        elif wire == 5:
            if num == 2:
                (val,) = struct.unpack("<f", buf[i:i + 4])
            i += 4
        elif wire == 1:
            i += 8
        else:
            _, i = _read_varint(buf, i)
    return tag, val
