"""Training-UI web server (reference: ``deeplearning4j-ui``
``VertxUIServer`` / ``UIServer.getInstance().attach(storage)``, SURVEY
§5.5 — the "optional tiny web dashboard" half of the named TPU
equivalent; TensorBoard event files remain the primary dashboard).

A stdlib ``http.server`` on a background thread serving:

- ``/``                 — single-page dashboard (inline HTML/JS/SVG; no
                          external assets — this environment has no
                          egress, and the reference bundles its JS too)
- ``/api/tags``         — JSON list of scalar tags across attached stores
- ``/api/series?tag=t`` — JSON ``[[step, value], ...]`` for one tag
- ``/healthz``          — liveness
- ``/api/metrics``      — Prometheus text exposition of every profiler
                          counter/gauge/ledger, serving latency
                          quantiles, and the flight-recorder totals
                          (:func:`prometheus_text`)
- ``/api/infer``        — POST ``{"inputs": [[...], ...]}`` (optional
                          ``"slo_class"``) → the attached
                          :class:`parallel.serving.ServingEngine` (bucketed,
                          AOT-compiled, deadline-bounded); response carries
                          outputs + server-side latency. 503 until
                          ``attach_serving`` wires an engine; a load shed
                          (brownout / class queue budget) is a synchronous
                          429 with ``Retry-After`` from the measured queue
                          drain rate.

Any attached :class:`InMemoryStatsStorage` (queried live) or JSONL path
written by :class:`FileStatsStorage` (re-read per request) feeds the
charts; the page polls every 2 s, so a training run with a
``StatsListener`` attached renders a live loss curve exactly like the
reference's overview tab.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from .stats import (FileStatsStorage, InMemoryStatsStorage,
                    StatsStorage)


def _prom_escape(value: Any) -> str:
    """Label-VALUE escaping per the text-exposition spec (0.0.4):
    backslash, double-quote and line-feed."""
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _prom_escape_help(text: str) -> str:
    """HELP-text escaping per the spec: only backslash and line-feed
    (quotes are legal in help text, unlike in label values)."""
    return str(text).replace("\\", r"\\").replace("\n", r"\n")


def prometheus_text() -> str:
    """The ``GET /api/metrics`` payload: Prometheus text exposition
    (format 0.0.4) of the whole observability surface — every
    ``OpProfiler`` counter (and the gauge-set subset as real gauges),
    every timing section, every derived ledger (``OpProfiler.LEDGERS`` —
    the same list ``/api/health`` and ``print_statistics`` render), the
    serving tier's rolling latency quantiles, and the flight recorder's
    own totals. Label values carry the repo-internal slash-names
    (``trace/mln_fit_step``) verbatim; metric names are fixed conformant
    families, so any Prometheus scraper ingests this without config."""
    from ..common import flightrec
    from ..common.profiler import OpProfiler

    prof = OpProfiler.get()
    lines: List[str] = []

    def family(name: str, mtype: str, help_text: str, samples) -> None:
        samples = list(samples)
        if not samples:
            return
        lines.append(f"# HELP {name} {_prom_escape_help(help_text)}")
        lines.append(f"# TYPE {name} {mtype}")
        for labels, value in samples:
            if isinstance(value, float):
                value = round(value, 9)
            if labels:
                lab = ",".join(f'{k}="{_prom_escape(v)}"'
                               for k, v in sorted(labels.items()))
                lines.append(f"{name}{{{lab}}} {value}")
            else:
                lines.append(f"{name} {value}")

    counters = prof.get_counters()
    gauges = prof.gauge_names()
    family("dl4j_counter_total", "counter",
           "OpProfiler event counters, labeled by counter name",
           (({"name": k}, v) for k, v in sorted(counters.items())
            if k not in gauges))
    family("dl4j_gauge", "gauge",
           "OpProfiler level gauges (absolute, last-write-wins)",
           (({"name": k}, v) for k, v in sorted(counters.items())
            if k in gauges))
    sections = prof.get_statistics()
    family("dl4j_section_seconds_total", "counter",
           "cumulative wall time per OpProfiler section",
           (({"section": k}, s["total_s"])
            for k, s in sorted(sections.items())))
    family("dl4j_section_count_total", "counter",
           "entry count per OpProfiler section",
           (({"section": k}, s["count"])
            for k, s in sorted(sections.items())))
    family("dl4j_section_max_seconds", "gauge",
           "longest single entry per OpProfiler section",
           (({"section": k}, s["max_s"])
            for k, s in sorted(sections.items())))
    ledger_samples = []
    for label, stats in prof.ledger_stats().items():
        for k, v in sorted(stats.items()):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            ledger_samples.append(({"ledger": label, "key": k}, v))
    family("dl4j_ledger", "gauge",
           "derived ledger values (OpProfiler *_stats())", ledger_samples)
    try:
        from ..parallel.serving import serving_health

        health = serving_health()
    except Exception:          # serving tier absent/unimportable: no rows
        health = {}
    latency_samples: List[Tuple[Dict[str, str], float]] = []
    for q, key in (("0.5", "latency_p50_ms"), ("0.99", "latency_p99_ms")):
        if key in health:
            latency_samples.append(({"quantile": q}, health[key]))
    # per-SLO-class quantiles (class label values pass through
    # _prom_escape like every other label — class names are caller data)
    for cls, cl in sorted(health.get("class_latency", {}).items()):
        for q, key in (("0.5", "p50_ms"), ("0.99", "p99_ms")):
            if key in cl:
                latency_samples.append(
                    ({"class": cls, "quantile": q}, cl[key]))
    family("dl4j_serving_latency_ms", "gauge",
           "rolling serving latency quantiles across live engines "
           "(fleet-wide, and per SLO class when classified)",
           latency_samples)
    try:
        from ..common import watchtower

        alert_rows = sorted(watchtower.alert_states().items())
    except Exception:          # watchtower absent: no rows
        alert_rows = []
    family("dl4j_alert_state", "gauge",
           "watchtower SLO alert state (0 ok / 1 warn / 2 page)",
           (({"slo": slo}, state) for slo, state in alert_rows))
    fr = flightrec.stats()
    family("dl4j_flightrec_events_total", "counter",
           "flight-recorder events ever appended", [({}, fr["events_total"])])
    family("dl4j_flightrec_dropped_total", "counter",
           "flight-recorder events evicted by ring overflow",
           [({}, fr["dropped"])])
    family("dl4j_flightrec_enabled", "gauge",
           "1 when the flight recorder is recording",
           [({}, int(fr["enabled"]))])
    family("dl4j_flightrec_buffered", "gauge",
           "events currently held in the ring", [({}, fr["buffered"])])
    return "\n".join(lines) + "\n"


class _JsonlTailCache:
    """Parsed-record cache for attached JSONL stats files.

    Re-parsing the whole file on every ``/api/series`` poll is O(file) per
    request and the dashboard polls every 2 s — a long run's stats file
    would dominate the server. Entries are keyed on ``(mtime_ns, size)``:
    an exact match returns the cached records; growth of an append-only
    file (the ``FileStatsStorage`` contract) parses only the appended tail
    from the cached byte offset. A rewrite falls back to a full reparse —
    detected by a shrink below the cached offset OR a changed leading-
    bytes prefix (a restarted run recreating the path can reach a size
    past the old offset between polls; the prefix check catches it
    without hashing the file). A torn final line (mid-write, no trailing
    newline) is left unparsed with the offset NOT advanced past it, so it
    is retried complete on a later request."""

    PREFIX_LEN = 64

    def __init__(self) -> None:
        self._state: Dict[str, dict] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.tail_reads = 0
        self.full_reads = 0

    def stats(self) -> Dict[str, int]:
        return {"hits": self.hits, "tail_reads": self.tail_reads,
                "full_reads": self.full_reads,
                "paths": len(self._state)}

    def read(self, path: str) -> List[dict]:
        st = os.stat(path)
        sig = (st.st_mtime_ns, st.st_size)
        with self._lock:
            ent = self._state.get(path)
            if ent is not None and ent["sig"] == sig:
                self.hits += 1
                return ent["records"]
            with open(path, "rb") as f:
                prefix = f.read(self.PREFIX_LEN)
                if ent is not None and st.st_size >= ent["offset"] \
                        and prefix == ent["prefix"]:
                    offset, records = ent["offset"], list(ent["records"])
                    self.tail_reads += 1
                else:
                    offset, records = 0, []
                    self.full_reads += 1
                f.seek(offset)
                data = f.read()
            end = data.rfind(b"\n") + 1
            for line in data[:end].splitlines():
                if not line.strip():
                    continue
                try:
                    records.append(json.loads(line.decode()))
                except (ValueError, UnicodeDecodeError):
                    continue
            self._state[path] = {"sig": sig, "offset": offset + end,
                                 "records": records, "prefix": prefix}
            return records

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>deeplearning4j-tpu UI</title>
<style>
 body{font-family:system-ui,sans-serif;margin:24px;background:#fafafa}
 h1{font-size:18px} .tag{margin:18px 0}
 svg{background:#fff;border:1px solid #ddd} .axis{stroke:#999}
 text{font-size:11px;fill:#555} polyline{fill:none;stroke:#2a6fdb;stroke-width:1.5}
 .latest{color:#2a6fdb;font-weight:600}
</style></head><body>
<h1>deeplearning4j-tpu training UI</h1>
<div id="health" style="color:#666;font-size:12px;margin:-8px 0 14px"></div>
<div id="charts"></div>
<div id="sdgraph"></div>
<script>
function esc(s){const d=document.createElement('div');d.textContent=s;return d.innerHTML;}
function mib(b){return (b/1048576).toFixed(0)+' MiB';}
async function health(){
  try{
    const h = await (await fetch('/api/health')).json();
    let parts = ['backend '+(h.backend||'?'),
                 'up '+(h.uptime_s||0)+'s',
                 (h.records||0)+' records'];
    for (const d of (h.devices||[])){
      if (d.bytes_in_use !== undefined)
        parts.push('dev'+d.id+' '+mib(d.bytes_in_use)+'/'+mib(d.bytes_limit));
    }
    if (h.live_buffers)
      parts.push(h.live_buffers.count+' live buffers ('+mib(h.live_buffers.bytes)+')');
    if (h.host && h.host.rss_bytes)
      parts.push('host rss '+mib(h.host.rss_bytes));
    document.getElementById('health').textContent = parts.join(' — ');
  }catch(e){}
}
health(); setInterval(health, 5000);
async function refresh(){
  const tags = await (await fetch('/api/tags')).json();
  const root = document.getElementById('charts');
  for (const tag of tags){
    const pts = await (await fetch('/api/series?tag='+encodeURIComponent(tag))).json();
    if (!pts.length) continue;
    let div = document.getElementById('c_'+tag);
    if (!div){
      div = document.createElement('div'); div.className='tag'; div.id='c_'+tag;
      root.appendChild(div);
    }
    const W=640,H=180,P=36;
    const xs=pts.map(p=>p[0]), ys=pts.map(p=>p[1]);
    const x0=Math.min(...xs), x1=Math.max(...xs)||1;
    const y0=Math.min(...ys), y1=Math.max(...ys);
    const sx=s=>P+(W-2*P)*(s-x0)/Math.max(x1-x0,1e-9);
    const sy=v=>H-P-(H-2*P)*(v-y0)/Math.max(y1-y0,1e-9);
    const line=pts.map(p=>sx(p[0]).toFixed(1)+','+sy(p[1]).toFixed(1)).join(' ');
    div.innerHTML = '<b>'+esc(tag)+'</b> <span class="latest">'+
      ys[ys.length-1].toPrecision(5)+'</span> (step '+xs[xs.length-1]+')<br>'+
      '<svg width="'+W+'" height="'+H+'">'+
      '<line class="axis" x1="'+P+'" y1="'+(H-P)+'" x2="'+(W-P)+'" y2="'+(H-P)+'"/>'+
      '<line class="axis" x1="'+P+'" y1="'+P+'" x2="'+P+'" y2="'+(H-P)+'"/>'+
      '<text x="'+P+'" y="'+(H-P+14)+'">'+x0+'</text>'+
      '<text x="'+(W-P-30)+'" y="'+(H-P+14)+'">'+x1+'</text>'+
      '<text x="2" y="'+(H-P)+'">'+y0.toPrecision(3)+'</text>'+
      '<text x="2" y="'+(P+4)+'">'+y1.toPrecision(3)+'</text>'+
      '<polyline points="'+line+'"/></svg>';
  }
}
refresh(); setInterval(refresh, 2000);
async function drawGraph(){
  const g = await (await fetch('/api/graph')).json();
  if (!g || !g.ops || !g.ops.length) return;
  const root = document.getElementById('sdgraph');
  const byDepth = {};
  for (const op of g.ops){
    (byDepth[op.depth] = byDepth[op.depth] || []).push(op);
  }
  let html = '<h1>SameDiff graph ('+g.n_ops+' ops, '+g.n_vars+
             ' vars)</h1><div class="glayers">';
  for (const d of Object.keys(byDepth).sort((a,b)=>a-b)){
    html += '<div class="glayer"><span class="gdepth">'+d+'</span>';
    for (const op of byDepth[d]){
      // escAttr: esc() covers text context only — attribute values also
      // need double quotes neutralized
      const t = esc(op.inputs.join(', ')).replace(/"/g,'&quot;');
      html += '<span class="gnode" title="in: '+t+
              '">'+esc(op.op)+' <i>'+esc(op.name)+'</i></span>';
    }
    html += '</div>';
  }
  root.innerHTML = html + '</div>';
}
drawGraph();
</script>
<style>
 .glayer{margin:3px 0}
 .gdepth{display:inline-block;width:26px;color:#999}
 .gnode{display:inline-block;background:#fff;border:1px solid #ccd;
        border-radius:4px;padding:2px 7px;margin:1px 3px;font-size:12px}
 .gnode i{color:#888;font-style:normal;font-size:10px}
</style>
</body></html>
"""


class UIServer:
    """Reference-shaped singleton: ``UIServer.get_instance().attach(...)``
    then ``enable()`` (reference ``attachUI``/port 9000 convention)."""

    _instance: Optional["UIServer"] = None
    _lock = threading.Lock()

    def __init__(self) -> None:
        self._stores: List[Any] = []
        self._paths: List[str] = []
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._jsonl = _JsonlTailCache()
        self._t0 = time.time()
        # records POSTed by RemoteUIStatsStorageRouter clients
        self._remote = InMemoryStatsStorage()
        self._stores.append(self._remote)
        self._serving = None    # ServingEngine behind /api/infer

    @classmethod
    def get_instance(cls) -> "UIServer":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    getInstance = get_instance

    # -- wiring ----------------------------------------------------------
    def attach(self, storage) -> "UIServer":
        """Attach an InMemoryStatsStorage (live queries) or a JSONL stats
        path / FileStatsStorage (re-read per request)."""
        if isinstance(storage, str):
            self._paths.append(storage)
        elif isinstance(storage, FileStatsStorage):
            self._paths.append(storage.path)
        elif hasattr(storage, "records"):
            self._stores.append(storage)
        else:
            raise TypeError(
                f"cannot attach {type(storage).__name__}: need an "
                "InMemoryStatsStorage, a FileStatsStorage, or a JSONL "
                "path (TensorBoardStatsStorage is viewed with "
                "`tensorboard --logdir`, not this server)")
        return self

    def attach_graph(self, source) -> "UIServer":
        """Attach a SameDiff graph for the dashboard's SameDiff section
        (reference: LogFileWriter's uigraphstatic log rendered by the UI's
        SameDiff tab). ``source`` is a SameDiff instance, a structure dict
        from ``graph_structure()``, or a ``LogFileWriter`` log path
        (re-read per request — live like the JSONL stats)."""
        from .graph_log import graph_structure

        if isinstance(source, str):
            self._graph_path = source
            self._graph = None
        elif isinstance(source, dict):
            self._graph = source
            self._graph_path = None
        else:
            self._graph = graph_structure(source)
            self._graph_path = None
        return self

    def _graph_payload(self):
        path = getattr(self, "_graph_path", None)
        if path is not None:
            from .graph_log import read_graph_log

            return read_graph_log(path)["graph"] or {}
        return getattr(self, "_graph", None) or {}

    def attach_serving(self, engine) -> "UIServer":
        """Expose a :class:`parallel.serving.ServingEngine` (or any object
        with deadline-bounded ``output(ndarray)``) on ``POST /api/infer``.
        Replica retirement/resurrection stays inside the engine — the
        endpoint never needs to know a replica died."""
        self._serving = engine
        return self

    def detach_all(self) -> None:
        self._stores = [self._remote]
        self._paths = []
        self._graph = None
        self._graph_path = None
        self._serving = None

    # -- data ------------------------------------------------------------
    def _records(self) -> List[Dict[str, Any]]:
        """All SCALAR records across attached stores and JSONL paths.
        JSONL files go through the tail cache (only the appended tail is
        parsed per request); histogram records (no "value" field — the
        TensorBoard backends render those) are filtered out here."""
        recs: List[Dict[str, Any]] = []
        for s in self._stores:
            recs.extend(getattr(s, "records", []))
        for p in self._paths:
            try:
                recs.extend(r for r in self._jsonl.read(p) if "value" in r)
            except (OSError, ValueError):
                pass
        return recs

    def health(self) -> Dict[str, Any]:
        """The /api/health payload: process uptime, attached-source census,
        JSONL-cache effectiveness, the live device/host memory telemetry
        from ``common.system_info.memory_summary`` (per-device PJRT stats
        + the jax live-buffer census), the self-healing ledger (supervisor
        restarts / watchdog fires / backoff waits + injected-fault
        counters), the collective-exchange ledger (bytes per collective
        kind, ZeRO-1 sharded-updater footprint, encoded-exchange density),
        the elastic ledger (online resizes, grow-back probes, the live
        worker gauge), the pipeline ledger (live stage gauge, remaps,
        microbatches, measured bubble fraction), the inference-pool census
        (live/retired/resurrected replicas), and the serving ledger
        (requests/batches, bucket fill ratio, pad waste, queue-depth
        high-water, rolling p50/p99 latency, traces-after-warmup)."""
        from ..common.profiler import OpProfiler
        from ..common.system_info import memory_summary
        from ..parallel.inference import pool_health
        from ..parallel.serving import serving_health

        n = sum(len(getattr(s, "records", ())) for s in self._stores)
        for p in self._paths:
            try:
                # counts from the tail cache — no full-list materialization
                n += sum(1 for r in self._jsonl.read(p) if "value" in r)
            except (OSError, ValueError):
                pass
        from ..common import flightrec

        prof = OpProfiler.get()
        # every derived profiler ledger rides OpProfiler.LEDGERS — the
        # same list /api/metrics and print_statistics iterate, so a new
        # ledger (e.g. the xprof "xla" roofline) can never be
        # metrics-only by accident. The serving section stays the MERGED
        # view (counters + per-engine latency quantiles).
        ledgers = {label: getattr(prof, attr)()
                   for label, attr in OpProfiler.LEDGERS
                   if label != "serving"}
        ledgers["serving"] = serving_health()
        # operators find the evidence from here: the newest incident
        # report (or blackbox when no watchtower ever assembled one)
        try:
            from ..common import watchtower
            last_incident = watchtower.last_incident()
        except Exception:
            last_incident = None
        return {"status": "ok",
                "last_incident": last_incident,
                "uptime_s": round(time.time() - self._t0, 1),
                "stores": len(self._stores),
                "paths": len(self._paths),
                "records": n,
                "jsonl_cache": self._jsonl.stats(),
                **ledgers,
                "flightrec": flightrec.stats(),
                "inference": pool_health(),
                **memory_summary()}

    def sessions(self) -> List[str]:
        return sorted({str(r.get("session", "")) for r in self._records()})

    def tags(self) -> List[str]:
        """Tag list; session-qualified as "session/tag" when records from
        more than one session are attached (two workers posting the same
        tag must chart as two series, not one interleaved sawtooth —
        reference UI keys by session)."""
        recs = self._records()
        sessions = {str(r.get("session", "")) for r in recs}
        if len(sessions) > 1:
            return sorted({f"{r.get('session', '')}/{r['tag']}"
                           for r in recs})
        return sorted({r["tag"] for r in recs})

    def series(self, tag: str,
               session: Optional[str] = None) -> List[Tuple[int, float]]:
        """Step-sorted (step, value) series for a tag. ``session`` filters
        to one session; a "session/tag"-qualified tag (as emitted by
        ``tags()`` in multi-session mode) is split the same way."""
        recs = self._records()
        if session is None and "/" in tag \
                and tag not in {r["tag"] for r in recs}:
            # qualified, not literal: split at the longest KNOWN session
            # prefix (session ids may themselves contain "/")
            sessions = {str(r.get("session", "")) for r in recs}
            for cand in sorted(
                    (s for s in sessions if tag.startswith(s + "/")),
                    key=len, reverse=True):
                session, tag = cand, tag[len(cand) + 1:]
                break
        return sorted((r["step"], r["value"]) for r in recs
                      if r["tag"] == tag
                      and (session is None
                           or str(r.get("session", "")) == session))

    # -- server ----------------------------------------------------------
    def enable(self, port: int = 9000) -> int:
        """Start serving (reference default port 9000; pass 0 for an
        ephemeral port). Returns the bound port."""
        if self._httpd is not None:
            return self._httpd.server_address[1]
        ui = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):    # quiet
                pass

            def _send(self, body: bytes, ctype: str, code: int = 200,
                      headers: Optional[Dict[str, str]] = None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                u = urlparse(self.path)
                if u.path == "/":
                    self._send(_PAGE.encode(), "text/html; charset=utf-8")
                elif u.path == "/healthz":
                    self._send(b"ok", "text/plain")
                elif u.path == "/api/health":
                    self._send(json.dumps(ui.health()).encode(),
                               "application/json")
                elif u.path == "/api/metrics":
                    self._send(prometheus_text().encode(),
                               "text/plain; version=0.0.4; charset=utf-8")
                elif u.path == "/api/tags":
                    self._send(json.dumps(ui.tags()).encode(),
                               "application/json")
                elif u.path == "/api/sessions":
                    self._send(json.dumps(ui.sessions()).encode(),
                               "application/json")
                elif u.path == "/api/graph":
                    self._send(json.dumps(ui._graph_payload()).encode(),
                               "application/json")
                elif u.path == "/api/series":
                    q = parse_qs(u.query)
                    tag = q.get("tag", [""])[0]
                    session = q.get("session", [None])[0]
                    self._send(
                        json.dumps(ui.series(tag, session=session)).encode(),
                        "application/json")
                elif u.path == "/api/trace":
                    # the flight-recorder ring as a Perfetto-loadable
                    # Chrome trace; ?corr= narrows to one incident
                    from ..common import flightrec

                    q = parse_qs(u.query)
                    corr = q.get("corr", [None])[0]
                    self._send(
                        json.dumps(flightrec.chrome_trace(corr=corr)).encode(),
                        "application/json")
                elif u.path == "/api/incidents":
                    from ..common import watchtower

                    q = parse_qs(u.query)
                    iid = q.get("id", [None])[0]
                    if iid is None:
                        self._send(
                            json.dumps(watchtower.incidents()).encode(),
                            "application/json")
                    else:
                        match = [i for i in watchtower.incidents()
                                 if i["id"] == iid]
                        if not match:
                            self._send(f"no incident {iid!r}".encode(),
                                       "text/plain", 404)
                        else:
                            try:
                                with open(match[0]["path"], "rb") as f:
                                    body = f.read()
                            except OSError as e:
                                self._send(f"incident file unreadable: "
                                           f"{e}".encode(), "text/plain",
                                           500)
                            else:
                                self._send(body, "application/json")
                else:
                    self._send(b"not found", "text/plain", 404)

            def _infer(self):
                # the serving endpoint: one JSON request → one bucketed,
                # deadline-bounded engine call. Thread-per-request
                # (ThreadingHTTPServer) feeds the engine's continuous
                # batcher, so concurrent HTTP clients coalesce into
                # shared bucket dispatches exactly like direct callers.
                import math

                import numpy as np

                from ..parallel.serving import Overloaded, OversizeRequest

                engine = getattr(ui, "_serving", None)
                if engine is None:
                    self._send(b"no serving engine attached "
                               b"(UIServer.attach_serving)", "text/plain",
                               503)
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n).decode())
                    inputs = np.asarray(body["inputs"], dtype=np.float32)
                    slo = body.get("slo_class")
                except (ValueError, KeyError, TypeError) as e:
                    self._send(f"bad request: {e}".encode(), "text/plain",
                               400)
                    return
                t0 = time.monotonic()
                try:
                    # kwarg only when classified: a plain
                    # ParallelInference behind this endpoint accepts no
                    # slo_class, and must keep working unclassified
                    out = (engine.output(inputs, slo_class=slo)
                           if slo is not None else engine.output(inputs))
                except Overloaded as e:
                    # the load-shed contract: synchronous 429 with a
                    # Retry-After derived from the measured queue drain
                    # rate (integer seconds per RFC 9110, rounded up)
                    self._send(
                        str(e).encode(), "text/plain", 429,
                        headers={"Retry-After":
                                 str(max(1, math.ceil(e.retry_after_s)))})
                    return
                except OversizeRequest as e:
                    self._send(str(e).encode(), "text/plain", 413)
                    return
                except ValueError as e:      # shape/rank mismatch
                    self._send(str(e).encode(), "text/plain", 400)
                    return
                except TimeoutError as e:    # deadline expired in queue
                    self._send(str(e).encode(), "text/plain", 504)
                    return
                except RuntimeError as e:    # pool retired / shut down
                    self._send(str(e).encode(), "text/plain", 503)
                    return
                except Exception as e:
                    # a model/XLA failure scattered through the future
                    # must reach the client as a status code, not a
                    # dropped connection
                    self._send(f"inference failed: "
                               f"{type(e).__name__}: {e}".encode(),
                               "text/plain", 500)
                    return
                payload = {"outputs": out.to_numpy().tolist(),
                           "shape": list(out.shape),
                           "latency_ms": round(
                               (time.monotonic() - t0) * 1e3, 3)}
                self._send(json.dumps(payload).encode(),
                           "application/json")

            def do_POST(self):
                # remote stats ingestion (reference
                # RemoteUIStatsStorageRouter: workers POST their updates
                # to the UI server)
                u = urlparse(self.path)
                if u.path == "/api/infer":
                    self._infer()
                    return
                if u.path != "/api/post":
                    self._send(b"not found", "text/plain", 404)
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    recs = json.loads(self.rfile.read(n).decode())
                    if isinstance(recs, dict):
                        recs = [recs]
                    # validate the WHOLE batch before inserting any record
                    # (a 400 must mean nothing was stored, or a client
                    # retry would duplicate the good prefix)
                    parsed = [(str(rec.get("session", "")),
                               str(rec["tag"]), int(rec["step"]),
                               float(rec["value"])) for rec in recs]
                except (ValueError, KeyError, TypeError,
                        AttributeError) as e:
                    self._send(f"bad record: {e}".encode(), "text/plain",
                               400)
                    return
                for session, tag, step, value in parsed:
                    ui._remote.put_scalar(session, tag, step, value)
                self._send(b"ok", "text/plain")

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self._httpd.server_address[1]

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None


class RemoteUIStatsStorageRouter(StatsStorage):
    """StatsStorage that POSTs scalars to a remote :class:`UIServer`
    (reference ``RemoteUIStatsStorageRouter`` — how Spark workers fed the
    driver-hosted UI; here: how any process feeds a central dashboard).

    ``put_scalar`` only enqueues (never blocks the training loop); a
    daemon sender thread drains the bounded queue in small batches,
    best-effort — when the server is unreachable or the queue is full,
    records drop rather than stall training."""

    def __init__(self, url: str, queue_size: int = 4096,
                 timeout: float = 2.0):
        import queue
        import threading

        self.url = url.rstrip("/")
        self.timeout = float(timeout)
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._closed = False
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def put_scalar(self, session, tag, step, value) -> None:
        import queue

        try:
            self._q.put_nowait({"session": session, "tag": tag,
                                "step": int(step),
                                "value": float(value)})
        except queue.Full:
            pass    # best-effort: drop under backpressure

    def _drain(self) -> None:
        import queue
        import urllib.request

        while not self._closed:
            try:
                batch = [self._q.get(timeout=0.25)]
            except queue.Empty:
                continue
            while len(batch) < 256:
                try:
                    batch.append(self._q.get_nowait())
                except queue.Empty:
                    break
            req = urllib.request.Request(
                self.url + "/api/post", data=json.dumps(batch).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=self.timeout):
                    pass
            except OSError:
                pass    # server down: drop the batch

    def flush(self, deadline: float = 5.0) -> None:
        """Best-effort wait for the queue to drain (tests/shutdown)."""
        import time

        t0 = time.time()
        while not self._q.empty() and time.time() - t0 < deadline:
            time.sleep(0.02)
        time.sleep(0.1)     # let the in-flight batch land

    def close(self) -> None:
        self.flush()
        self._closed = True
