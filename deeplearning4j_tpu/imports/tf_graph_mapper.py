"""TF frozen-GraphDef import → SameDiff.

Reference: nd4j-api ``org/nd4j/imports/graphmapper/tf/TFGraphMapper.java``
(legacy direct mapper) and the Kotlin ``samediff-import-tensorflow``
(``ImportGraph.kt`` + ``MappingProcess`` rule tables) — SURVEY.md §2.1, §3.4.

Design (idiomatic rebuild, not a translation):

- **Table-driven**: one small mapper per TF op name (the ``@tf_op`` registry =
  the reference's ``ImportClassMapping``/``OpMappingRegistry``), each emitting
  ops from this package's registry into a ``SameDiff`` graph. The whole
  imported graph then lowers to ONE jitted XLA module like any other SameDiff
  graph — there is no separate "imported graph" execution engine.
- **Structural-argument folding**: XLA needs static shapes/axes/permutations,
  but TF graphs compute them with tensor subgraphs (``Shape`` →
  ``StridedSlice`` → ``Pack`` → ``Reshape``). Nodes whose inputs are all
  static are folded to numpy constants at import time, and ``Shape`` resolves
  through jax ``eval_shape`` over the partially-built graph, so those
  subgraphs disappear instead of defeating the compiler.
- TF protos are parsed with the locally installed tensorflow (import-time
  dependency only — execution never touches TF).

Conformance: ``tests/test_tf_import.py`` generates golden graphs with the
local TF (SURVEY.md §4.3 harness shape: freeze → import → execute → compare
within per-op tolerance).

Supported TF surface (round-5 statement of scope): FROZEN inference
GraphDefs over the 138 registered op names (``supported_tf_ops()``) — the
closure covering MLPs, CNNs (Conv2D/DepthwiseConv2d/pooling/FusedBatchNorm
inference/image resize), and transformer encoders (BERT-base end-to-end,
benched). Conformance: 328 generated golden cases + coverage gates in
``tests/test_tf_conformance.py`` (every mapped op targeted or ledgered).
Deliberately OUT of scope, erroring with actionable messages rather than
importing wrong:

- ``FusedBatchNorm(is_training=True)`` — freeze for inference first;
  training uses this framework's own BatchNormalization layer (importing
  TF's training-mode statistics contract would duplicate it with subtly
  different EMA semantics);
- ``GatherV2(batch_dims>0)`` and ``Conv2D(padding=EXPLICIT)`` — not
  emitted by frozen classifier/encoder graphs;
- TF2 control flow (``StatelessWhile``/``If``): frozen inference graphs
  constant-fold these away; build control flow natively with
  ``SameDiff.cond``/``while_loop``;
- resource variables/queues/datasets other than ``IteratorGetNext`` (which
  maps to placeholders);
- string/ragged dtypes (no XLA representation).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..autodiff.samediff import SameDiff, SDVariable

_TF_OPS: Dict[str, Callable] = {}


class UnsupportedTFOpError(NotImplementedError):
    def __init__(self, op: str, node_name: str):
        super().__init__(
            f"TF op {op!r} (node {node_name!r}) has no mapper; register one "
            f"with @tf_op({op!r}) in deeplearning4j_tpu/imports/tf_graph_mapper.py")
        self.op = op


def tf_op(*names: str):
    """Register a mapper for one or more TF op names (the MappingProcess
    analog: mapper(ctx) -> SDVariable | tuple[SDVariable, ...])."""

    def deco(fn):
        for n in names:
            _TF_OPS[n] = fn
        return fn

    return deco


def supported_tf_ops() -> List[str]:
    return sorted(_TF_OPS)


# --------------------------------------------------------------------------
# attr / proto helpers (lazy TF import)


def _tf():
    import tensorflow as tf  # local install; import-time only

    return tf


def _np_dtype(tf_enum: int):
    return _tf().dtypes.as_dtype(tf_enum).as_numpy_dtype


def _make_ndarray(tensor_proto) -> np.ndarray:
    from tensorflow.python.framework import tensor_util

    return np.asarray(tensor_util.MakeNdarray(tensor_proto))


class _Ctx:
    """Per-node mapper context: typed attr access, resolved inputs, static
    values, and shape inference over the partially built graph."""

    def __init__(self, imp: "_Importer", node):
        self.imp = imp
        self.node = node
        self.sd = imp.sd
        self.name = node.name
        self.data_inputs = [i for i in node.input if not i.startswith("^")]

    # --- attrs ---------------------------------------------------------
    def attr(self, name: str, default=None):
        if name not in self.node.attr:
            return default
        a = self.node.attr[name]
        kind = a.WhichOneof("value")
        if kind == "i":
            return int(a.i)
        if kind == "f":
            return float(a.f)
        if kind == "b":
            return bool(a.b)
        if kind == "s":
            return a.s.decode()
        if kind == "type":
            return np.dtype(_np_dtype(a.type))
        if kind == "shape":
            return [d.size if d.size >= 0 else None for d in a.shape.dim]
        if kind == "list":
            lst = a.list
            for field in ("i", "f", "b", "s", "type"):
                vals = getattr(lst, field)
                if len(vals):
                    if field == "s":
                        return [v.decode() for v in vals]
                    if field == "type":
                        return [np.dtype(_np_dtype(v)) for v in vals]
                    return list(vals)
            return []
        if kind == "tensor":
            return _make_ndarray(a.tensor)
        return default

    # --- inputs --------------------------------------------------------
    def n_in(self) -> int:
        return len(self.data_inputs)

    def var(self, i: int) -> SDVariable:
        return self.imp.resolve_var(self.data_inputs[i])

    def vars(self, start: int = 0, end: Optional[int] = None) -> List[SDVariable]:
        return [self.imp.resolve_var(t)
                for t in self.data_inputs[start:end]]

    def static(self, i: int) -> np.ndarray:
        """Static (import-time) value of input i — must come from a constant
        or folded subgraph (standard table-driven-importer requirement for
        structural args: shapes, axes, permutations)."""
        t = self.data_inputs[i]
        v = self.imp.static_value(t)
        if v is None:
            raise ValueError(
                f"input {i} ({t!r}) of node {self.name!r} ({self.node.op}) "
                "must be statically resolvable (constant/shape subgraph); "
                "dynamic values are not supported for structural arguments "
                "under XLA's static-shape model")
        return v

    def static_or_none(self, i: int) -> Optional[np.ndarray]:
        if i >= self.n_in():
            return None
        return self.imp.static_value(self.data_inputs[i])

    def shape_of_input(self, i: int) -> Tuple[int, ...]:
        return self.imp.infer_shape(self.data_inputs[i])

    def emit(self, op_name: str, inputs: Sequence[Any], n_outputs=None, **kw):
        return self.sd._add_op(op_name, list(inputs), name=self.name,
                               n_outputs=n_outputs, **kw)


# --------------------------------------------------------------------------


class _Importer:
    def __init__(self, graph_def, input_shapes: Optional[Dict[str, Sequence[int]]] = None):
        self.gd = graph_def
        self.sd = SameDiff.create()
        self.input_shapes = dict(input_shapes or {})
        self._env: Dict[str, SDVariable] = {}       # tf tensor name -> SDVariable
        self._static: Dict[str, np.ndarray] = {}    # tf tensor name -> ndarray
        self._shape_cache: Dict[str, Tuple[int, ...]] = {}
        self.placeholders: List[str] = []
        self.outputs: List[str] = []

    # --- name plumbing --------------------------------------------------
    @staticmethod
    def _canon(tensor_name: str) -> str:
        return tensor_name if ":" in tensor_name else tensor_name + ":0"

    def _bind(self, node_name: str, outs) -> None:
        if isinstance(outs, SDVariable):
            outs = (outs,)
        for i, v in enumerate(outs):
            self._env[f"{node_name}:{i}"] = v

    def resolve_var(self, tensor_name: str) -> SDVariable:
        key = self._canon(tensor_name)
        if key in self._env:
            return self._env[key]
        # a folded static that was never materialized as a graph constant
        sval = self._static.get(key)
        if sval is not None:
            v = self.sd.constant(key.replace(":", "_"), sval)
            self._env[key] = v
            return v
        raise KeyError(f"unresolved TF tensor {tensor_name!r}")

    def static_value(self, tensor_name: str) -> Optional[np.ndarray]:
        return self._static.get(self._canon(tensor_name))

    def set_static(self, node_name: str, value: np.ndarray, out_index: int = 0):
        self._static[f"{node_name}:{out_index}"] = np.asarray(value)

    # --- shape inference over the partial graph -------------------------
    def infer_shape(self, tensor_name: str,
                    assume_unknown: Optional[int] = None) -> Tuple[int, ...]:
        """Shape of a tensor in the partially built graph. With
        ``assume_unknown``, unknown placeholder dims (batch=None in frozen
        inference graphs) are substituted with that value instead of
        raising — use ONLY when the caller reads dims that don't depend on
        the substituted ones (e.g. pooling H/W with batch unknown)."""
        import jax

        key = self._canon(tensor_name)
        if assume_unknown is None and key in self._shape_cache:
            return self._shape_cache[key]
        var = self.resolve_var(key)
        vinfo = self.sd._vars[var.name]
        if vinfo.shape is not None and all(d is not None for d in vinfo.shape):
            shp = tuple(int(d) for d in vinfo.shape)
            self._shape_cache[key] = shp
            return shp
        fn = self.sd._make_fn((var.name,), training=False)
        params = {n: jax.ShapeDtypeStruct(np.asarray(v.value).shape,
                                          np.asarray(v.value).dtype)
                  for n, v in self.sd._vars.items()
                  if v.vtype == "VARIABLE"}
        ph = {}
        for n in self.sd.placeholders():
            pshape = self.sd._vars[n].shape
            if pshape is None or any(d is None for d in pshape):
                # unknown RANK can't be assumed away — only unknown dims
                if assume_unknown is None or pshape is None:
                    raise ValueError(
                        f"cannot infer shape of {tensor_name!r}: placeholder "
                        f"{n!r} has unknown dims — pass input_shapes={{...}} "
                        "to the importer")
                pshape = [assume_unknown if d is None else d for d in pshape]
            pdt = np.dtype(self.sd._vars[n].dtype)
            ph[n] = jax.ShapeDtypeStruct(tuple(pshape), pdt)
        key_struct = jax.ShapeDtypeStruct((2,), np.uint32)
        out = jax.eval_shape(fn, params, ph, key_struct)
        shp = tuple(int(d) for d in out[0].shape)
        if assume_unknown is None:
            self._shape_cache[key] = shp
        return shp

    # --- main loop ------------------------------------------------------
    def run(self) -> SameDiff:
        order = _topo_order(self.gd.node)
        consumed: Dict[str, int] = {}
        for node in self.gd.node:
            for t in node.input:
                if not t.startswith("^"):
                    consumed[self._canon(t)] = consumed.get(self._canon(t), 0) + 1

        for node in order:
            opn = node.op
            if opn in ("NoOp", "Assert", "CheckNumerics"):
                continue
            if opn == "Const":
                val = _make_ndarray(node.attr["value"].tensor)
                self.set_static(node.name, val)
                # materialized lazily in resolve_var only when consumed as a
                # tensor — structural consts never enter the graph
                continue
            if opn in ("Placeholder", "PlaceholderWithDefault"):
                self._import_placeholder(node)
                continue
            if opn == "IteratorGetNext":
                self._import_iterator_get_next(node)
                continue
            ctx = _Ctx(self, node)
            folder = _FOLDERS.get(opn)
            if folder is not None:
                statics = [self.static_value(t) for t in ctx.data_inputs]
                if all(s is not None for s in statics):
                    try:
                        res = folder(ctx, statics)
                    except Exception:
                        res = None
                    if res is not None:
                        if not isinstance(res, (list, tuple)):
                            res = (res,)
                        for i, r in enumerate(res):
                            self.set_static(node.name, r, i)
                        continue
            if opn == "Shape":
                shp = self.infer_shape(ctx.data_inputs[0])
                self.set_static(node.name, np.asarray(
                    shp, dtype=ctx.attr("out_type", np.dtype(np.int32))))
                continue
            mapper = _TF_OPS.get(opn)
            if mapper is None:
                raise UnsupportedTFOpError(opn, node.name)
            outs = mapper(ctx)
            if outs is not None:
                self._bind(node.name, outs)

        # graph outputs: nodes NONE of whose output ports are consumed.
        # (A node with one consumed port and dangling siblings — TopKV2
        # when only indices are read, IdentityN — is an intermediate, not
        # an output; TF freezing wraps real outputs in Identity nodes.)
        for node in self.gd.node:
            key = f"{node.name}:0"
            if key not in self._env:
                continue
            i, any_consumed = 0, False
            while f"{node.name}:{i}" in self._env:
                if consumed.get(f"{node.name}:{i}", 0):
                    any_consumed = True
                i += 1
            if not any_consumed:
                self.outputs.append(self._env[key].name)
        return self.sd

    def _import_placeholder(self, node) -> None:
        dtype = node.attr["dtype"].type
        shape = None
        if "shape" in node.attr:
            shape = [d.size if d.size >= 0 else None
                     for d in node.attr["shape"].shape.dim]
        if node.name in self.input_shapes:
            shape = list(self.input_shapes[node.name])
        v = self.sd.placeholder(node.name, shape=shape,
                                dtype=np.dtype(_np_dtype(dtype)).name)
        self._bind(node.name, v)
        self.placeholders.append(v.name)

    def _import_iterator_get_next(self, node) -> None:
        """BERT-style input nodes (SURVEY.md §3.4): each output becomes a
        placeholder named <node>:i so the dataset binds positionally."""
        dtypes = self.attr_list_types(node, "output_types")
        shapes = self.attr_list_shapes(node, "output_shapes")
        outs = []
        for i, dt in enumerate(dtypes):
            shape = shapes[i] if i < len(shapes) else None
            name = node.name if i == 0 else f"{node.name}_{i}"
            if name in self.input_shapes:
                shape = list(self.input_shapes[name])
            v = self.sd.placeholder(name, shape=shape, dtype=np.dtype(dt).name)
            self.placeholders.append(v.name)
            outs.append(v)
        self._bind(node.name, tuple(outs))

    @staticmethod
    def attr_list_types(node, name):
        if name not in node.attr:
            return []
        return [np.dtype(_np_dtype(t)) for t in node.attr[name].list.type]

    @staticmethod
    def attr_list_shapes(node, name):
        if name not in node.attr:
            return []
        return [[d.size if d.size >= 0 else None for d in s.dim]
                for s in node.attr[name].list.shape]


def _topo_order(nodes) -> List[Any]:
    """Kahn's algorithm (iterative — deep op chains would blow Python's
    recursion limit under a DFS)."""
    from collections import deque

    by_name = {n.name: n for n in nodes}
    indeg: Dict[str, int] = {}
    dependents: Dict[str, List[str]] = {}
    for n in nodes:
        deps = {t[1:] if t.startswith("^") else t.split(":")[0]
                for t in n.input}
        deps = [d for d in deps if d in by_name]
        indeg[n.name] = len(deps)
        for d in deps:
            dependents.setdefault(d, []).append(n.name)
    queue = deque(n.name for n in nodes if indeg[n.name] == 0)
    order: List[Any] = []
    while queue:
        nm = queue.popleft()
        order.append(by_name[nm])
        for m in dependents.get(nm, ()):
            indeg[m] -= 1
            if indeg[m] == 0:
                queue.append(m)
    if len(order) != len(nodes):
        stuck = [n for n, d in indeg.items() if d > 0][:5]
        raise ValueError(f"graph has a cycle (frozen graphs are acyclic); "
                         f"unresolved: {stuck}")
    return order


# --------------------------------------------------------------------------
# numpy folding of structural subgraphs


def _strided_slice_spec(ctx: _Ctx, begin, end, strides):
    begin = np.asarray(begin).tolist()
    end = np.asarray(end).tolist()
    strides = (np.asarray(strides).tolist() if strides is not None
               else [1] * len(begin))
    bm = ctx.attr("begin_mask", 0)
    em = ctx.attr("end_mask", 0)
    ellipsis = ctx.attr("ellipsis_mask", 0)
    new_axis = ctx.attr("new_axis_mask", 0)
    shrink = ctx.attr("shrink_axis_mask", 0)
    spec = []
    for i in range(len(begin)):
        if ellipsis & (1 << i):
            spec.append(Ellipsis)
        elif new_axis & (1 << i):
            spec.append(None)
        elif shrink & (1 << i):
            spec.append(int(begin[i]))
        else:
            b = None if bm & (1 << i) else int(begin[i])
            e = None if em & (1 << i) else int(end[i])
            spec.append(slice(b, e, int(strides[i])))
    return tuple(spec)


_FOLDERS: Dict[str, Callable] = {
    "Identity": lambda ctx, s: s[0],
    "Add": lambda ctx, s: s[0] + s[1],
    "AddV2": lambda ctx, s: s[0] + s[1],
    "Sub": lambda ctx, s: s[0] - s[1],
    "Mul": lambda ctx, s: s[0] * s[1],
    "RealDiv": lambda ctx, s: s[0] / s[1],
    "FloorDiv": lambda ctx, s: s[0] // s[1],
    "FloorMod": lambda ctx, s: np.mod(s[0], s[1]),
    "Maximum": lambda ctx, s: np.maximum(s[0], s[1]),
    "Minimum": lambda ctx, s: np.minimum(s[0], s[1]),
    "Neg": lambda ctx, s: -s[0],
    "Cast": lambda ctx, s: s[0].astype(_np_dtype(ctx.node.attr["DstT"].type)),
    "Pack": lambda ctx, s: np.stack(s, axis=ctx.attr("axis", 0)),
    "Unpack": lambda ctx, s: [np.squeeze(a, ctx.attr("axis", 0)) for a in
                              np.split(s[0], s[0].shape[ctx.attr("axis", 0)],
                                       ctx.attr("axis", 0))],
    "ConcatV2": lambda ctx, s: np.concatenate(s[:-1], axis=int(s[-1])),
    "ExpandDims": lambda ctx, s: np.expand_dims(s[0], int(s[1])),
    "Squeeze": lambda ctx, s: np.squeeze(
        s[0], tuple(ctx.attr("squeeze_dims", []) or ctx.attr("axis", []))
        or None),
    "Reshape": lambda ctx, s: np.reshape(s[0], np.asarray(s[1]).tolist()),
    "Transpose": lambda ctx, s: np.transpose(s[0], np.asarray(s[1]).tolist()),
    "Div": lambda ctx, s: (np.trunc(np.divide(s[0], s[1])).astype(
        np.result_type(s[0], s[1])) if np.issubdtype(
            np.result_type(s[0], s[1]), np.integer) else s[0] / s[1]),
    # .item() (not int()) keeps float ranges exact: int(0.5) == 0 would
    # poison the step (conformance case Range.float_step pinned this)
    "Range": lambda ctx, s: np.arange(
        np.asarray(s[0]).item(), np.asarray(s[1]).item(),
        np.asarray(s[2]).item()).astype(np.result_type(s[0], s[1], s[2])),
    "GatherV2": lambda ctx, s: np.take(s[0], s[1].astype(np.int64),
                                       axis=int(s[2]) if len(s) > 2 else 0),
    "StridedSlice": lambda ctx, s: s[0][_strided_slice_spec(ctx, s[1], s[2], s[3])],
    "Slice": lambda ctx, s: s[0][tuple(
        slice(int(b), int(b) + int(sz) if int(sz) >= 0 else None)
        for b, sz in zip(np.asarray(s[1]).tolist(), np.asarray(s[2]).tolist()))],
    "Prod": lambda ctx, s: np.prod(s[0], axis=tuple(np.atleast_1d(s[1]).tolist())
                                   if len(s) > 1 else None,
                                   keepdims=ctx.attr("keep_dims", False)),
    "Sum": lambda ctx, s: np.sum(s[0], axis=tuple(np.atleast_1d(s[1]).tolist())
                                 if len(s) > 1 else None,
                                 keepdims=ctx.attr("keep_dims", False)),
    "Fill": lambda ctx, s: np.full(np.asarray(s[0]).tolist(), s[1]),
    "ZerosLike": lambda ctx, s: np.zeros_like(s[0]),
    "OnesLike": lambda ctx, s: np.ones_like(s[0]),
    # single-arg Where has a data-dependent output shape, which XLA can't
    # trace — but a STATIC condition (mask known at freeze, e.g. BERT's
    # fixed position masks) folds to a constant coordinate list here
    "Where": lambda ctx, s: (np.argwhere(s[0]).astype(np.int64)
                             if len(s) == 1 else None),
}


# --------------------------------------------------------------------------
# mappers — elementwise


def _binary(op_name):
    def m(ctx: _Ctx):
        return ctx.emit(op_name, [ctx.var(0), ctx.var(1)])

    return m


_BINARY = {
    "Add": "add", "AddV2": "add", "Sub": "subtract", "Mul": "multiply",
    "RealDiv": "divide", "FloorDiv": "floordiv",
    "FloorMod": "floormod", "Maximum": "maximum", "Minimum": "minimum",
    "Pow": "pow", "SquaredDifference": "squaredsubtract",
    "TruncateDiv": "truncatediv", "Atan2": "atan2",
    "Equal": "equals", "NotEqual": "not_equals", "Greater": "greater",
    "GreaterEqual": "greater_equal", "Less": "less", "LessEqual": "less_equal",
    "LogicalAnd": "boolean_and", "LogicalOr": "boolean_or",
}
for _tf_name, _our in _BINARY.items():
    tf_op(_tf_name)(_binary(_our))


def _unary(op_name, **fixed_kw):
    def m(ctx: _Ctx):
        return ctx.emit(op_name, [ctx.var(0)], **fixed_kw)

    return m


_UNARY = {
    "Abs": "abs", "Neg": "neg", "Exp": "exp", "Log": "log", "Log1p": "log1p",
    "Sqrt": "sqrt", "Rsqrt": "rsqrt", "Square": "square", "Sign": "sign",
    "Floor": "floor", "Ceil": "ceil", "Round": "round", "Rint": "rint",
    "Sin": "sin", "Cos": "cos", "Tan": "tan", "Asin": "asin", "Acos": "acos",
    "Atan": "atan", "Sinh": "sinh", "Cosh": "cosh", "Tanh": "tanh",
    "Asinh": "asinh", "Acosh": "acosh", "Atanh": "atanh",
    "Erf": "erf", "Erfc": "erfc", "Sigmoid": "sigmoid", "Relu": "relu",
    "Relu6": "relu6", "Selu": "selu", "Softplus": "softplus",
    "Softsign": "softsign", "Reciprocal": "reciprocal", "LogicalNot": "boolean_not",
    "IsNan": "isnan", "IsInf": "isinf", "IsFinite": "isfinite",
    "Expm1": "expm1",
}
for _tf_name, _our in _UNARY.items():
    tf_op(_tf_name)(_unary(_our))


@tf_op("Elu")
def _elu(ctx):
    return ctx.emit("elu", [ctx.var(0)])


@tf_op("LeakyRelu")
def _leaky_relu(ctx):
    return ctx.emit("leakyrelu", [ctx.var(0)], alpha=ctx.attr("alpha", 0.2))


@tf_op("Identity", "StopGradient", "PreventGradient", "Snapshot", "EnsureShape")
def _identity(ctx):
    return ctx.emit("identity", [ctx.var(0)])


@tf_op("IdentityN")
def _identity_n(ctx):
    return tuple(ctx.emit("identity", [v]) for v in ctx.vars())


@tf_op("Cast")
def _cast(ctx):
    dst = np.dtype(_np_dtype(ctx.node.attr["DstT"].type))
    return ctx.emit("cast", [ctx.var(0)], dtype=dst.name)


@tf_op("Select", "SelectV2")
def _select(ctx):
    return ctx.emit("select", [ctx.var(0), ctx.var(1), ctx.var(2)])


@tf_op("ClipByValue")
def _clip_by_value(ctx):
    return ctx.emit("clip_by_value", [ctx.var(0)],
                    clip_min=float(ctx.static(1)), clip_max=float(ctx.static(2)))


@tf_op("Div")
def _div(ctx):
    # TF Div: C semantics — integer inputs truncate toward zero, floats
    # divide exactly (conformance case Div.v1_int pinned this)
    dt = ctx.attr("T")
    if dt is not None and np.issubdtype(np.dtype(dt), np.integer):
        return ctx.emit("truncatediv", [ctx.var(0), ctx.var(1)])
    return ctx.emit("divide", [ctx.var(0), ctx.var(1)])


# --------------------------------------------------------------------------
# mappers — reductions

_REDUCE = {"Sum": "reduce_sum", "Mean": "reduce_mean", "Max": "reduce_max",
           "Min": "reduce_min", "Prod": "reduce_prod", "All": "all", "Any": "any"}


def _reduction(op_name):
    def m(ctx: _Ctx):
        if ctx.n_in() > 1:
            # structural arg: must resolve statically — a silent fall-through
            # to all-axes reduction would produce wrong shapes without error
            dims = tuple(np.atleast_1d(ctx.static(1)).tolist())
        else:
            dims = None
        return ctx.emit(op_name, [ctx.var(0)], dims=dims,
                        keep_dims=ctx.attr("keep_dims", False))

    return m


for _tf_name, _our in _REDUCE.items():
    tf_op(_tf_name)(_reduction(_our))


@tf_op("ArgMax")
def _argmax(ctx):
    dim = int(ctx.static(1)) if ctx.n_in() > 1 else 0
    out = ctx.emit("argmax", [ctx.var(0)], dims=(dim,))
    odt = ctx.attr("output_type")
    if odt is not None and np.dtype(odt) != np.int32:
        out = ctx.sd._add_op("cast", [out], dtype=np.dtype(odt).name)
    return out


@tf_op("ArgMin")
def _argmin(ctx):
    dim = int(ctx.static(1)) if ctx.n_in() > 1 else 0
    out = ctx.emit("argmin", [ctx.var(0)], dims=(dim,))
    odt = ctx.attr("output_type")
    if odt is not None and np.dtype(odt) != np.int32:
        out = ctx.sd._add_op("cast", [out], dtype=np.dtype(odt).name)
    return out


# --------------------------------------------------------------------------
# mappers — shape / indexing


@tf_op("Reshape")
def _reshape(ctx):
    shape = np.asarray(ctx.static(1)).tolist()
    if any(d == -1 for d in shape):
        in_shape = ctx.shape_of_input(0)
        known = int(np.prod([d for d in shape if d != -1]))
        total = int(np.prod(in_shape))
        shape = [total // max(known, 1) if d == -1 else d for d in shape]
    return ctx.emit("reshape", [ctx.var(0), tuple(int(d) for d in shape)])


@tf_op("Transpose")
def _transpose(ctx):
    perm = tuple(int(d) for d in np.asarray(ctx.static(1)).tolist())
    return ctx.emit("permute", [ctx.var(0), perm])


@tf_op("ExpandDims")
def _expand_dims(ctx):
    return ctx.emit("expand_dims", [ctx.var(0)], axis=int(ctx.static(1)))


@tf_op("Squeeze")
def _squeeze(ctx):
    dims = ctx.attr("squeeze_dims", []) or ctx.attr("axis", [])
    return ctx.emit("squeeze", [ctx.var(0)],
                    axis=tuple(int(d) for d in dims) if dims else None)


@tf_op("ConcatV2")
def _concat(ctx):
    axis = int(ctx.static(ctx.n_in() - 1))
    return ctx.emit("concat", ctx.vars(0, ctx.n_in() - 1), axis=axis)


@tf_op("Pack")
def _pack(ctx):
    return ctx.emit("stack", ctx.vars(), axis=ctx.attr("axis", 0))


@tf_op("Unpack")
def _unpack(ctx):
    num = ctx.attr("num")
    return ctx.emit("unstack", [ctx.var(0)], axis=ctx.attr("axis", 0),
                    n_outputs=num)


@tf_op("Split")
def _split(ctx):
    num = ctx.attr("num_split")
    axis = int(ctx.static(0))
    return ctx.emit("split", [ctx.var(1)], num_split=num, axis=axis,
                    n_outputs=num)


@tf_op("SplitV")
def _split_v(ctx):
    sizes = tuple(int(s) for s in np.asarray(ctx.static(1)).tolist())
    axis = int(ctx.static(2))
    return ctx.emit("split_v", [ctx.var(0)], sizes=sizes, axis=axis,
                    n_outputs=len(sizes))


@tf_op("Slice")
def _slice(ctx):
    begin = tuple(int(b) for b in np.asarray(ctx.static(1)).tolist())
    sizes = np.asarray(ctx.static(2)).tolist()
    in_shape = ctx.shape_of_input(0)
    sizes = tuple(int(in_shape[i] - begin[i]) if s == -1 else int(s)
                  for i, s in enumerate(sizes))
    return ctx.emit("slice", [ctx.var(0), begin, sizes])


def _encode_slice_spec(spec) -> List[List]:
    """numpy index spec → JSON-safe encoding (SameDiff graphs must
    serialize; slice/Ellipsis objects are not JSON types)."""
    out: List[List] = []
    for s in spec:
        if isinstance(s, slice):
            out.append(["slice", s.start, s.stop, s.step])
        elif s is None:
            out.append(["newaxis"])
        elif s is Ellipsis:
            out.append(["ellipsis"])
        else:
            out.append(["idx", int(s)])
    return out


@tf_op("StridedSlice")
def _strided_slice(ctx):
    spec = _strided_slice_spec(ctx, ctx.static(1), ctx.static(2), ctx.static(3))
    return ctx.sd._add_op("tf_strided_slice", [ctx.var(0)], name=ctx.name,
                          spec=_encode_slice_spec(spec))


@tf_op("Tile")
def _tile(ctx):
    reps = tuple(int(r) for r in np.asarray(ctx.static(1)).tolist())
    return ctx.emit("tile", [ctx.var(0), reps])


@tf_op("GatherV2", "Gather")
def _gather(ctx):
    if ctx.attr("batch_dims", 0):
        raise UnsupportedTFOpError("GatherV2(batch_dims>0)", ctx.name)
    axis = int(ctx.static(2)) if ctx.n_in() > 2 else 0
    return ctx.emit("gather", [ctx.var(0), ctx.var(1)], axis=axis)


@tf_op("GatherNd")
def _gather_nd(ctx):
    return ctx.emit("gather_nd", [ctx.var(0), ctx.var(1)])


@tf_op("Pad", "PadV2")
def _pad(ctx):
    paddings = tuple(tuple(int(v) for v in row)
                     for row in np.asarray(ctx.static(1)).tolist())
    cval = float(ctx.static(2)) if ctx.n_in() > 2 else 0.0
    return ctx.emit("pad", [ctx.var(0), paddings], constant_value=cval)


@tf_op("MirrorPad")
def _mirror_pad(ctx):
    paddings = tuple(tuple(int(v) for v in row)
                     for row in np.asarray(ctx.static(1)).tolist())
    mode = ctx.attr("mode", "REFLECT").lower()
    return ctx.emit("pad", [ctx.var(0), paddings], mode=mode)


@tf_op("BroadcastTo")
def _broadcast_to(ctx):
    shape = tuple(int(d) for d in np.asarray(ctx.static(1)).tolist())
    return ctx.emit("broadcast_to", [ctx.var(0), shape])


@tf_op("Fill")
def _fill(ctx):
    shape = tuple(int(d) for d in np.asarray(ctx.static(0)).tolist())
    return ctx.emit("fill", [shape, ctx.var(1)])


@tf_op("Range")
def _range(ctx):
    # jnp.arange needs Python scalars (XLA static shapes): Range is a
    # structural op — require static inputs and fold to a constant.
    # (The _FOLDERS entry normally handles this; this path covers Range
    # nodes whose inputs resolved static but weren't folded.)
    start, limit, delta = (np.asarray(ctx.static(i)).item()
                           for i in range(3))
    val = np.arange(start, limit, delta).astype(
        np.dtype(ctx.attr("Tidx", np.dtype(np.int32))))
    return ctx.sd.constant(ctx.name.replace("/", "_") + "_range", val)


@tf_op("ZerosLike")
def _zeros_like(ctx):
    return ctx.emit("zeros_as", [ctx.var(0)])


@tf_op("OnesLike")
def _ones_like(ctx):
    return ctx.emit("ones_as", [ctx.var(0)])


@tf_op("Size")
def _size(ctx):
    return ctx.emit("size", [ctx.var(0)])


@tf_op("Rank")
def _rank(ctx):
    return ctx.emit("rank", [ctx.var(0)])


@tf_op("ReverseV2")
def _reverse(ctx):
    dims = tuple(int(d) for d in np.atleast_1d(ctx.static(1)).tolist())
    return ctx.emit("reverse", [ctx.var(0), dims])


@tf_op("OneHot")
def _one_hot(ctx):
    depth = int(ctx.static(1))
    on = float(ctx.static(2)) if ctx.n_in() > 2 else 1.0
    off = float(ctx.static(3)) if ctx.n_in() > 3 else 0.0
    return ctx.emit("one_hot", [ctx.var(0)], depth=depth, on_value=on,
                    off_value=off, axis=ctx.attr("axis", -1))


@tf_op("Cumsum")
def _cumsum(ctx):
    return ctx.emit("cumsum", [ctx.var(0)], axis=int(ctx.static(1)),
                    exclusive=ctx.attr("exclusive", False),
                    reverse=ctx.attr("reverse", False))


@tf_op("Where")
def _where(ctx):
    if ctx.n_in() == 1:
        # static conditions fold in _FOLDERS before reaching here; a
        # PLACEHOLDER-dependent condition has a data-dependent output
        # shape XLA cannot trace
        raise UnsupportedTFOpError(
            "Where(cond) single-arg with non-static condition "
            "(data-dependent output shape)", ctx.name)
    return ctx.emit("where", [ctx.var(0), ctx.var(1), ctx.var(2)])


# --------------------------------------------------------------------------
# mappers — linear algebra / NN


@tf_op("MatMul")
def _matmul(ctx):
    return ctx.emit("matmul", [ctx.var(0), ctx.var(1)],
                    transpose_x=ctx.attr("transpose_a", False),
                    transpose_y=ctx.attr("transpose_b", False))


@tf_op("BatchMatMul", "BatchMatMulV2", "BatchMatMulV3")
def _batch_matmul(ctx):
    return ctx.emit("batched_gemm", [ctx.var(0), ctx.var(1)],
                    transpose_x=ctx.attr("adj_x", False),
                    transpose_y=ctx.attr("adj_y", False))


@tf_op("Einsum")
def _einsum(ctx):
    eq = ctx.attr("equation")
    return ctx.sd._add_op("einsum", ctx.vars(), name=ctx.name, equation=eq)


@tf_op("BiasAdd")
def _bias_add(ctx):
    fmt = ctx.attr("data_format", "NHWC")
    if fmt == "NCHW":
        return ctx.emit("bias_add", [ctx.var(0), ctx.var(1)], data_format="NCHW")
    return ctx.emit("add", [ctx.var(0), ctx.var(1)])  # broadcast on last axis


@tf_op("Softmax")
def _softmax(ctx):
    return ctx.emit("softmax", [ctx.var(0)], axis=-1)


@tf_op("LogSoftmax")
def _log_softmax(ctx):
    return ctx.emit("log_softmax", [ctx.var(0)], axis=-1)


@tf_op("L2Loss")
def _l2_loss(ctx):
    x = ctx.var(0)
    sq = ctx.sd._add_op("square", [x])
    s = ctx.sd._add_op("reduce_sum", [sq])
    return ctx.emit("multiply", [s, 0.5])


def _tf_conv_args(ctx, rank=2):
    fmt = ctx.attr("data_format", "NHWC")
    strides = ctx.attr("strides", [1] * (rank + 2))
    dilations = ctx.attr("dilations", [1] * (rank + 2))
    if fmt.startswith("NC"):
        s = strides[2:2 + rank]
        d = dilations[2:2 + rank]
    else:
        s = strides[1:1 + rank]
        d = dilations[1:1 + rank]
    padding = ctx.attr("padding", "VALID")
    if padding == "EXPLICIT":
        raise UnsupportedTFOpError("Conv EXPLICIT padding", ctx.name)
    return fmt, tuple(s), tuple(d), padding


@tf_op("Conv2D")
def _conv2d(ctx):
    fmt, s, d, pad = _tf_conv_args(ctx)
    w = ctx.var(1)
    # TF kernel HWIO -> reference OIHW
    w_oihw = ctx.sd._add_op("permute", [w, (3, 2, 0, 1)])
    return ctx.emit("conv2d", [ctx.var(0), w_oihw], strides=s, padding=pad,
                    dilation=d, data_format="NCHW" if fmt == "NCHW" else "NHWC")


@tf_op("DepthwiseConv2dNative")
def _depthwise_conv2d(ctx):
    fmt, s, d, pad = _tf_conv_args(ctx)
    w = ctx.var(1)
    # TF kernel [kH,kW,C,mult] -> reference [mult,C,kH,kW]
    w_r = ctx.sd._add_op("permute", [w, (3, 2, 0, 1)])
    return ctx.emit("depthwise_conv2d", [ctx.var(0), w_r], strides=s,
                    padding=pad, dilation=d,
                    data_format="NCHW" if fmt == "NCHW" else "NHWC")


def _tf_pool_args(ctx):
    fmt = ctx.attr("data_format", "NHWC")
    ks = ctx.attr("ksize", [1, 1, 1, 1])
    st = ctx.attr("strides", [1, 1, 1, 1])
    if fmt.startswith("NC"):
        k, s = ks[2:4], st[2:4]
    else:
        k, s = ks[1:3], st[1:3]
    return fmt, tuple(k), tuple(s), ctx.attr("padding", "VALID")


@tf_op("MaxPool")
def _max_pool(ctx):
    fmt, k, s, pad = _tf_pool_args(ctx)
    return ctx.emit("maxpool2d", [ctx.var(0)], kernel=k, strides=s, padding=pad,
                    data_format="NCHW" if fmt == "NCHW" else "NHWC")


@tf_op("AvgPool")
def _avg_pool(ctx):
    fmt, k, s, pad = _tf_pool_args(ctx)
    df = "NCHW" if fmt == "NCHW" else "NHWC"
    pooled = ctx.emit("avgpool2d", [ctx.var(0)], kernel=k, strides=s,
                      padding=pad, data_format=df)
    if pad != "SAME":
        return pooled
    # TF AvgPool EXCLUDES padding from the divisor; ops/nn averages over
    # the full kernel area. Pads/kernel/strides are static, so correct
    # with a precomputed (oh, ow) scale — shared machinery with the ONNX
    # count_include_pad=0 path (conformance case AvgPool.k3s1_same).
    # assume_unknown=1: frozen graphs commonly have batch=None; only the
    # spatial dims feed the scale and they don't depend on batch.
    from .onnx_import import _avgpool_exclude_pad_scale, _same_pad_begin_end

    shp = ctx.imp.infer_shape(ctx.data_inputs[0], assume_unknown=1)
    hw = shp[2:4] if df == "NCHW" else shp[1:3]
    # the correction is a host-precomputed per-pixel divisor, so the
    # SPATIAL dims must be genuinely static: probing with two assumed
    # values exposes dims that merely inherited the placeholder's unknown
    # (computing the divisor from an assumed H=W=1 would silently rescale
    # the whole feature map)
    shp2 = ctx.imp.infer_shape(ctx.data_inputs[0], assume_unknown=2)
    if hw != (shp2[2:4] if df == "NCHW" else shp2[1:3]):
        raise UnsupportedTFOpError(
            "AvgPool(SAME) exclude-pad correction needs static spatial "
            "dims, but they are unknown in the graph (unknown batch alone "
            "is fine) — pass input_shapes={...} to the importer", ctx.name)
    begin, end = _same_pad_begin_end(hw, k, s)
    if not any(begin) and not any(end):
        return pooled
    scale = _avgpool_exclude_pad_scale(
        hw, k, s, begin, end, np.dtype(ctx.attr("T", np.dtype(np.float32))))
    scale = scale[None, None] if df == "NCHW" else scale[None, :, :, None]
    c = ctx.sd.constant(ctx.name.replace("/", "_") + "_cip_scale", scale)
    return ctx.sd._add_op("multiply", [pooled, c])


@tf_op("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3")
def _fused_batch_norm(ctx):
    if ctx.attr("is_training", True):
        raise UnsupportedTFOpError(
            "FusedBatchNorm(is_training=True) — freeze the graph for "
            "inference first", ctx.name)
    fmt = ctx.attr("data_format", "NHWC")
    x, gamma, beta, mean, var = (ctx.var(0), ctx.var(1), ctx.var(2),
                                 ctx.var(3), ctx.var(4))
    out = ctx.emit("batchnorm", [x, mean, var, gamma, beta],
                   epsilon=ctx.attr("epsilon", 1e-3),
                   axis=1 if fmt == "NCHW" else -1)
    # V3 emits 6 outputs; only y (index 0) is consumed in frozen graphs
    return (out, mean, var, mean, var, mean)


@tf_op("ResizeBilinear", "ResizeNearestNeighbor", "ResizeBicubic")
def _resize_image(ctx):
    """TF image-resize nodes (detection/zoo graph staple, round 5);
    size input must be static (XLA static shapes). Attrs map 1:1 onto
    the registry resize ops (all NHWC like TF)."""
    size = np.asarray(ctx.static(1)).reshape(-1)
    h, w = int(size[0]), int(size[1])
    ac = bool(ctx.attr("align_corners", False))
    hp = bool(ctx.attr("half_pixel_centers", False))
    opn = ctx.node.op
    if opn == "ResizeNearestNeighbor":
        return ctx.emit("resize_nearest", [ctx.var(0)], height=h, width=w,
                        align_corners=ac, half_pixel_centers=hp)
    if opn == "ResizeBicubic":
        if ac or not hp:
            # the registry bicubic implements TF2's half-pixel Keys
            # kernel; the legacy corner modes have no consumer graphs
            raise UnsupportedTFOpError(
                "ResizeBicubic(align_corners or legacy centers)", ctx.name)
        return ctx.emit("resize_bicubic", [ctx.var(0)], height=h, width=w)
    return ctx.emit("resize_bilinear", [ctx.var(0)], height=h, width=w,
                    align_corners=ac, half_pixel_centers=hp)


@tf_op("MatrixDiag", "MatrixDiagPart")
def _matrix_diag(ctx):
    table = {"MatrixDiag": "matrix_diag", "MatrixDiagPart": "matrix_diag_part"}
    return ctx.emit(table[ctx.node.op], [ctx.var(0)])


@tf_op("MatrixDiagV2", "MatrixDiagV3", "MatrixDiagPartV2", "MatrixDiagPartV3")
def _matrix_diag_v23(ctx):
    # TF2's tf.linalg.diag/diag_part emit the V3 ops (conformance corpus
    # caught the gap). Main-diagonal defaults map to the V1 semantics;
    # band extraction (k != 0) / explicit geometry are refused.
    part = "Part" in ctx.node.op

    def _static_int(i, default):
        if ctx.n_in() <= i:
            return default
        return [int(v) for v in np.atleast_1d(ctx.static(i)).tolist()]

    k = _static_int(1, [0])
    if part:
        padding = float(np.asarray(ctx.static(2)).item()) \
            if ctx.n_in() > 2 else 0.0
        nondefault = k != [0] or padding != 0.0
    else:
        num_rows = _static_int(2, [-1])
        num_cols = _static_int(3, [-1])
        padding = float(np.asarray(ctx.static(4)).item()) \
            if ctx.n_in() > 4 else 0.0
        nondefault = (k != [0] or num_rows != [-1] or num_cols != [-1]
                      or padding != 0.0)
    if nondefault:
        raise UnsupportedTFOpError(
            f"{ctx.node.op}(k/num_rows/num_cols/padding != defaults) — "
            "band diagonals are not mapped", ctx.name)
    return ctx.emit("matrix_diag_part" if part else "matrix_diag",
                    [ctx.var(0)])


@tf_op("TopKV2")
def _top_k(ctx):
    k = int(ctx.static(1))
    return ctx.emit("top_k", [ctx.var(0)], k=k, sorted=ctx.attr("sorted", True),
                    n_outputs=2)


@tf_op("SparseSoftmaxCrossEntropyWithLogits")
def _sparse_softmax_ce(ctx):
    # TF returns PER-EXAMPLE losses (plus a backprop tensor frozen graphs
    # never consume); the registry op reduces, so compose it unreduced
    logits, labels = ctx.var(0), ctx.var(1)
    logp = ctx.sd._add_op("log_softmax", [logits], axis=-1)
    lbl_oh = ctx.sd._add_op("one_hot", [labels],
                            depth=int(ctx.shape_of_input(0)[-1]))
    picked = ctx.sd._add_op("multiply", [logp, lbl_oh])
    per = ctx.sd._add_op("reduce_sum", [picked], dims=(-1,))
    return ctx.emit("neg", [per])


# --------------------------------------------------------------------------
# public API


class TFGraphMapper:
    """Reference-shaped entry (``TFGraphMapper.importGraph``)."""

    @staticmethod
    def import_graph(graph, input_shapes: Optional[Dict[str, Sequence[int]]] = None
                     ) -> SameDiff:
        gd = _as_graph_def(graph)
        imp = _Importer(gd, input_shapes)
        sd = imp.run()
        sd.tf_placeholders = list(imp.placeholders)
        sd.tf_outputs = list(imp.outputs)
        return sd

    importGraph = import_graph


def import_frozen_tf(path_or_graphdef,
                     input_shapes: Optional[Dict[str, Sequence[int]]] = None
                     ) -> SameDiff:
    """Reference ``SameDiff.importFrozenTF``: frozen GraphDef (.pb path, bytes,
    or proto) → SameDiff graph executable/trainable on TPU."""
    return TFGraphMapper.import_graph(path_or_graphdef, input_shapes)


def _as_graph_def(graph):
    from tensorflow.core.framework import graph_pb2

    if isinstance(graph, graph_pb2.GraphDef):
        return graph
    if isinstance(graph, (str,)):
        gd = graph_pb2.GraphDef()
        with open(graph, "rb") as f:
            gd.ParseFromString(f.read())
        return gd
    if isinstance(graph, bytes):
        gd = graph_pb2.GraphDef()
        gd.ParseFromString(graph)
        return gd
    if hasattr(graph, "as_graph_def"):
        return graph.as_graph_def()
    raise TypeError(f"cannot interpret {type(graph)} as a GraphDef")
