"""Keras FUNCTIONAL-model import → ComputationGraph.

Reference: dl4j-modelimport ``KerasModelImport.importKerasModelAndWeights``
→ ``KerasModel`` (the non-Sequential path: layer DAG from
``inbound_nodes``, merge layers → graph vertices; SURVEY.md §2.3). The
Sequential path lives in ``keras_import.py``; this module reuses its
per-layer weight-layout conversions (HWIO→OIHW etc.) by driving the same
mapper methods one layer at a time, and adds:

- DAG topology from Keras-3 ``inbound_nodes`` (``keras_history`` entries),
- merge layers → vertices (Add/Subtract/Multiply/Average/Maximum →
  ElementWiseVertex, Concatenate → MergeVertex — channel-dim concat, the
  NHWC axis=-1 contract),
- Flatten → identity node; the first Dense behind it gets its kernel rows
  permuted HWC→CHW AFTER graph type inference resolves the CNN shape
  (same exactness trick as the Sequential importer, deferred because a
  DAG's shapes are only known post-inference),
- NHWC input contract preserved via a transpose preprocessor on each
  input node.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..nn.conf import layers as L
from ..nn.conf.builder import NeuralNetConfiguration
from ..nn.conf.inputs import CNNInput, InputType, Preprocessor
from ..nn.graph import (ComputationGraph, ComputationGraphConfiguration,
                        DotProductVertex, ElementWiseVertex, MergeVertex)
from .keras_import import (UnsupportedKerasLayerError, _layer_weights,
                           _read_h5, _SequentialBuilder)

_MERGE_OPS = {"Add": "add", "Subtract": "subtract",
              "Multiply": "mul", "Average": "avg", "Maximum": "max",
              "Minimum": "min"}


def _call_sites(kl: Dict[str, Any]) -> List[List[Tuple[str, Optional[list]]]]:
    """Per CALL SITE: [(source name, source shape-or-None), ...] from
    Keras-3 (keras_history + shape) or Keras-2 ([[name, 0, 0, {}]])
    inbound_nodes. A layer invoked more than once (shared layer) has
    multiple call sites."""
    sites: List[List[Tuple[str, Optional[list]]]] = []

    def walk(obj, acc):
        if isinstance(obj, dict):
            if obj.get("class_name") == "__keras_tensor__":
                acc.append((obj["config"]["keras_history"][0],
                            obj["config"].get("shape")))
            else:
                for v in obj.get("args", []) if "args" in obj else []:
                    walk(v, acc)
        elif isinstance(obj, (list, tuple)):
            if (len(obj) >= 3 and isinstance(obj[0], str)
                    and isinstance(obj[1], int)):
                acc.append((obj[0], None))   # Keras-2 triplet
            else:
                for v in obj:
                    walk(v, acc)

    for node in kl.get("inbound_nodes", []):
        acc: List[Tuple[str, Optional[list]]] = []
        walk(node, acc)
        if acc:
            sites.append(acc)
    return sites


def _endpoints(spec) -> List[str]:
    """input_layers/output_layers: [name,0,0] or a list of them."""
    if not spec:
        return []
    if isinstance(spec[0], str):
        return [spec[0]]
    return [e[0] for e in spec]


def _convert_layer(kl: Dict[str, Any], f) -> Tuple[L.Layer, Optional[Callable]]:
    """One Keras layer → (our layer, weight setter), reusing the Sequential
    importer's mappers without its linear shape tracking."""
    sb = _SequentialBuilder()
    sb.cur_cnn = None           # disable sequential CNN tracking
    sb.input_type = InputType.feed_forward(1)  # satisfies guards; unused
    sb.add(kl, f)
    if len(sb.layers) == 1:
        return sb.layers[0], sb.weights[0]
    if len(sb.layers) == 2 and isinstance(sb.layers[1], L.ActivationLayer):
        # the leaky-relu split produces two layers; refuse rather than
        # silently drop the activation in a DAG context
        raise UnsupportedKerasLayerError(
            kl["class_name"],
            "activation='leaky_relu' kwarg inside a functional graph — use "
            "a separate LeakyReLU layer")
    raise UnsupportedKerasLayerError(kl["class_name"],
                                     "unexpected multi-layer expansion")


def import_functional(h5_path: str) -> ComputationGraph:
    f, cfg = _read_h5(h5_path)
    try:
        return import_functional_parsed(f, cfg)
    finally:
        f.close()


def import_functional_parsed(f, cfg) -> ComputationGraph:
    if True:   # indentation block kept minimal for the shared body below
        if cfg["class_name"] not in ("Functional", "Model"):
            raise UnsupportedKerasLayerError(
                cfg["class_name"], "import_functional expects a functional "
                "model; use import_keras_sequential_model_and_weights")
        layers_cfg = cfg["config"]["layers"]
        gb = (ComputationGraphConfiguration
              .graph_builder(NeuralNetConfiguration.builder())
              )
        input_types: Dict[str, InputType] = {}
        input_nhwc: Dict[str, bool] = {}
        setters: Dict[str, Optional[Callable]] = {}
        flatten_src: Dict[str, str] = {}     # flatten node -> its input
        dense_after_flatten: List[Tuple[str, str]] = []
        # shape-preserving chain member -> flatten's source (its per-feature
        # weights need the same row permute as the downstream Dense kernel)
        perfeature_after_flatten: Dict[str, str] = {}
        # (cls, name, flatten source) of layers that break the permute chain
        broken_chain: List[Tuple[str, str, str]] = []
        node_of: Dict[str, str] = {}         # keras name -> graph node name

        inputs = []
        for kl in layers_cfg:
            if kl["class_name"] == "InputLayer":
                c = kl["config"]
                name = c["name"]
                shape = c.get("batch_shape") or c.get("batch_input_shape")
                dims = list(shape[1:])
                if len(dims) == 3:
                    h, w, ch = dims
                    input_types[name] = InputType.convolutional(h, w, ch)
                    input_nhwc[name] = True
                elif len(dims) == 4:   # NDHWC (Conv3D / ConvLSTM2D inputs)
                    d, h, w, ch = dims
                    input_types[name] = InputType.convolutional_3d(
                        d, h, w, ch)
                    input_nhwc[name] = "ndhwc"
                elif len(dims) == 1:
                    input_types[name] = InputType.feed_forward(dims[0])
                    input_nhwc[name] = False
                elif len(dims) == 2:
                    input_types[name] = InputType.recurrent(dims[1], dims[0])
                    input_nhwc[name] = False
                else:
                    raise UnsupportedKerasLayerError("InputLayer",
                                                     f"rank {len(dims)}")
                inputs.append(name)
                node_of[name] = name
        gb.add_inputs(*inputs)

        # layers whose output keeps the flattened row ORDER intact — the
        # deferred Dense kernel permute must chain through them (the
        # Sequential importer's flatten_pending equivalent)
        _SHAPE_PRESERVING = {"Dropout", "Activation", "ReLU", "LeakyReLU",
                             "Softmax", "ELU", "AlphaDropout",
                             "GaussianDropout", "GaussianNoise", "PReLU",
                             "LayerNormalization", "BatchNormalization"}
        for kl in layers_cfg:
            cls = kl["class_name"]
            if cls == "InputLayer":
                continue
            c = kl.get("config", {})
            name = c.get("name", cls)
            sites = _call_sites(kl)
            if not sites:
                raise UnsupportedKerasLayerError(
                    cls, f"{name}: no inbound nodes")
            if len(sites) > 1:
                # a SHARED layer (applied at several graph positions) would
                # need one node per call site with tied weights; wiring all
                # sources into one node would silently drop inputs
                raise UnsupportedKerasLayerError(
                    cls, f"{name}: shared layers (multiple call sites) are "
                    "not supported")
            srcs = [node_of[s] for s, _ in sites[0]]
            src_shapes = [shape for _, shape in sites[0]]
            if cls in _MERGE_OPS or cls == "Concatenate":
                # a merge fed by a Flatten chain scrambles the flattened
                # row order beyond tracking — a downstream Dense would
                # import silently wrong; record for the post-build check
                for s in srcs:
                    if s in flatten_src:
                        broken_chain.append((cls, name, flatten_src[s]))
            if cls in _MERGE_OPS:
                gb.add_vertex(name, ElementWiseVertex(_MERGE_OPS[cls]),
                              *srcs)
            elif cls == "Concatenate":
                axis = c.get("axis", -1)
                ranks = {len(sh) for sh in src_shapes if sh is not None}
                rank = ranks.pop() if len(ranks) == 1 else None
                # channel concat only: axis -1 always; positive axes only
                # when they denote the channel dim for the known rank
                ok = axis == -1 or (rank is not None and axis == rank - 1)
                if not ok:
                    raise UnsupportedKerasLayerError(
                        "Concatenate",
                        f"{name}: axis={axis} on rank-{rank} inputs "
                        "(channel-dim concat only)")
                gb.add_vertex(name, MergeVertex(), *srcs)
            elif cls == "Dot":
                axes = c.get("axes", -1)
                ax = (axes if isinstance(axes, int)
                      else (axes[0] if len(set(axes)) == 1 else None))
                if ax not in (-1, 1):
                    raise UnsupportedKerasLayerError(
                        "Dot", f"{name}: axes={axes} (feature-axis dot of "
                        "two [B, F] inputs only)")
                gb.add_vertex(name, DotProductVertex(
                    normalize=bool(c.get("normalize", False))), *srcs)
            elif cls == "Masking":
                raise UnsupportedKerasLayerError(
                    "Masking",
                    f"{name}: in-graph mask propagation is wired for "
                    "Sequential models only (MultiLayerNetwork threads the "
                    "derived mask; ComputationGraph does not)")
            elif cls == "Flatten":
                gb.add_layer(name, L.FlattenLayer(), *srcs)
                # chain through an upstream Flatten (or chain member): a
                # Flatten of an already-flat tensor is an identity, so the
                # permute source stays the ORIGINAL CNN tensor
                flatten_src[name] = flatten_src.get(srcs[0], srcs[0])
            else:
                layer, setter = _convert_layer(kl, f)
                gb.add_layer(name, layer, *srcs)
                setters[name] = setter
                if getattr(layer, "shape_preserving", False):
                    # registered custom layer opted in (keras_import.py
                    # hook contract) — chain without per-feature permute
                    # bookkeeping (custom layers own their weight layout)
                    if srcs[0] in flatten_src:
                        flatten_src[name] = flatten_src[srcs[0]]
                elif cls in _SHAPE_PRESERVING and srcs[0] in flatten_src:
                    flatten_src[name] = flatten_src[srcs[0]]
                    # per-feature weights of chain members (LayerNorm
                    # gain/bias, PReLU alpha) see CHW-ordered activations
                    perfeature_after_flatten[name] = flatten_src[srcs[0]]
                if isinstance(layer, L.DenseLayer) and \
                        srcs[0] in flatten_src:
                    dense_after_flatten.append((name, flatten_src[srcs[0]]))
                elif cls not in _SHAPE_PRESERVING and \
                        not getattr(layer, "shape_preserving", False) and \
                        srcs[0] in flatten_src:
                    # the pending HWC->CHW row permute can't be tracked
                    # through this layer — refuse IF the flatten was over a
                    # CNN tensor (checked after build, when output types of
                    # the flatten source are known)
                    broken_chain.append((cls, name, flatten_src[srcs[0]]))
            node_of[name] = name

        outputs = _endpoints(cfg["config"].get("output_layers"))
        gb.set_outputs(*outputs)
        conf = gb.set_input_types(*[input_types[i] for i in inputs]).build()

        for bcls, bname, bsrc in broken_chain:
            if isinstance(conf.node_output_types[bsrc], CNNInput):
                raise UnsupportedKerasLayerError(
                    bcls,
                    f"{bname}: layer between Flatten and Dense does not "
                    "preserve the flattened row order; the HWC->CHW kernel "
                    "permute cannot be applied soundly")

        # NHWC/NDHWC input contract: transpose once on entry per image
        # input (channels-last arrays in, channels-first body)
        for iname in inputs:
            if input_nhwc[iname]:
                node = conf.nodes[iname]
                prev = node.preprocessors.get(0)
                perm = ((0, 4, 1, 2, 3)
                        if input_nhwc[iname] == "ndhwc" else (0, 3, 1, 2))
                nhwc = Preprocessor("NhwcToNchw",
                                    lambda x, _p=perm: x.transpose(_p),
                                    conf.node_output_types[iname])
                if prev is not None:
                    node.preprocessors[0] = Preprocessor(
                        f"NhwcToNchw+{prev.name}",
                        lambda x, p=prev, n=nhwc: p(n(x)), prev.out_type)
                else:
                    node.preprocessors[0] = nhwc

        net = ComputationGraph(conf).init()

        # weights (+ the deferred flatten→dense row permute)
        permute_for = dict(dense_after_flatten)
        from .keras_import import (_check_tree_shapes, _flatten_perm,
                                   _jnp_tree, _np_tree,
                                   _permute_per_feature)

        for name, setter in setters.items():
            if setter is None:
                continue
            params = _np_tree(net._params[name])
            if getattr(setter, "wants_state", False):
                state = {k: np.asarray(v)
                         for k, v in net._states[name].items()}
                setter(params, state)
                net._states[name] = {k: np.asarray(v, np.float32)
                                     for k, v in state.items()}
            else:
                setter(params)
            if name in permute_for:
                t = conf.node_output_types[permute_for[name]]
                if isinstance(t, CNNInput):
                    perm = _flatten_perm(
                        (t.channels, t.height, t.width))
                    params["W"] = np.asarray(params["W"])[perm]
            if name in perfeature_after_flatten:
                t = conf.node_output_types[perfeature_after_flatten[name]]
                if isinstance(t, CNNInput):
                    perm = _flatten_perm(
                        (t.channels, t.height, t.width))
                    _permute_per_feature(params, perm)
                    if net._states.get(name):    # BN mean/var
                        st = dict(net._states[name])
                        _permute_per_feature(st, perm)
                        net._states[name] = {
                            k: np.asarray(v, np.float32)
                            for k, v in st.items()}
            _check_tree_shapes(net._params[name], params, f"node {name!r}")
            net._params[name] = _jnp_tree(params)
        return net
