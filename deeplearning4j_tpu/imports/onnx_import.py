"""ONNX model import → SameDiff.

Reference: ``nd4j/samediff-import/samediff-import-onnx`` (Kotlin
``OnnxFrameworkImporter`` + ``OnnxMappingProcess`` rule tables) and the
``nd4j-onnxruntime`` interop module — SURVEY.md §2.1.

Architecture: the same table-driven design as ``tf_graph_mapper.py`` (round-2
importer), instantiated over the ONNX IR instead of TF GraphDef:

- one small mapper per ONNX op_type (the ``@onnx_op`` registry =
  ``OnnxOpMappingRegistry``), each emitting this package's registry ops into
  a ``SameDiff`` graph that lowers to ONE jitted XLA module;
- **structural-argument folding**: ONNX computes shapes/axes with tensor
  subgraphs too (``Shape`` → ``Gather`` → ``Unsqueeze`` → ``Concat`` →
  ``Reshape``); nodes whose inputs are all static fold to numpy constants at
  import time and ``Shape`` resolves through jax ``eval_shape``, so those
  subgraphs never reach the compiler;
- graph ``initializer`` tensors import as CONSTANT variables;
  ``SameDiff.convert_to_variables`` then makes any subset trainable — the
  same fine-tune flow the BERT/TF path uses;
- opset differences (attribute-vs-input ``axes``, ``Clip`` min/max inputs,
  ``Split`` sizes) are handled per-mapper via ``ctx.opset``.

The ONNX IR protos are compiled locally from the vendored ``onnx_ir.proto``
(the ``onnx`` pip package is not in this image; the schema is public and
stable). Conformance: ``tests/test_onnx_import.py`` builds ONNX graphs with
``tests/onnx_testlib.py`` and checks against torch.nn.functional semantics
(torch implements the ONNX operator contracts these mappers target).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..autodiff.samediff import SameDiff, SDVariable
from . import onnx_ir_pb2 as OIR

_ONNX_OPS: Dict[str, Callable] = {}


class UnsupportedOnnxOpError(NotImplementedError):
    def __init__(self, op: str, node_name: str):
        super().__init__(
            f"ONNX op {op!r} (node {node_name!r}) has no mapper; register "
            f"one with @onnx_op({op!r}) in "
            "deeplearning4j_tpu/imports/onnx_import.py")
        self.op = op


def onnx_op(*names: str):
    """Register a mapper for one or more ONNX op_types
    (mapper(ctx) -> SDVariable | tuple[SDVariable, ...])."""

    def deco(fn):
        for n in names:
            _ONNX_OPS[n] = fn
        return fn

    return deco


def supported_onnx_ops() -> List[str]:
    return sorted(_ONNX_OPS)


# --------------------------------------------------------------------------
# TensorProto → numpy

_DT = OIR.TensorProto
_NP_OF_DT = {
    _DT.FLOAT: np.float32, _DT.UINT8: np.uint8, _DT.INT8: np.int8,
    _DT.UINT16: np.uint16, _DT.INT16: np.int16, _DT.INT32: np.int32,
    _DT.INT64: np.int64, _DT.BOOL: np.bool_, _DT.FLOAT16: np.float16,
    _DT.DOUBLE: np.float64, _DT.UINT32: np.uint32, _DT.UINT64: np.uint64,
}


def tensor_to_numpy(t: "OIR.TensorProto") -> np.ndarray:
    if t.data_type == _DT.BFLOAT16:
        import jax.numpy as jnp

        raw = np.frombuffer(t.raw_data, dtype=np.uint16) if t.raw_data else \
            np.asarray(list(t.int32_data), dtype=np.uint16)
        return raw.view(jnp.bfloat16).reshape(tuple(t.dims))
    if t.data_type not in _NP_OF_DT:
        raise ValueError(f"unsupported ONNX tensor dtype {t.data_type}")
    dt = np.dtype(_NP_OF_DT[t.data_type])
    shape = tuple(t.dims)
    if t.raw_data:
        return np.frombuffer(t.raw_data, dtype=dt).reshape(shape).copy()
    if t.data_type == _DT.FLOAT16:
        # spec: fp16 without raw_data lives in int32_data as uint16 BIT
        # PATTERNS — reinterpret, never value-cast
        bits = np.asarray(list(t.int32_data), dtype=np.uint16)
        return bits.view(np.float16).reshape(shape)
    field = {
        _DT.FLOAT: t.float_data, _DT.DOUBLE: t.double_data,
        _DT.INT64: t.int64_data, _DT.UINT64: t.uint64_data,
    }.get(t.data_type, t.int32_data)
    return np.asarray(list(field), dtype=dt).reshape(shape)


def numpy_to_tensor(a: np.ndarray, name: str = "") -> "OIR.TensorProto":
    """Inverse of ``tensor_to_numpy`` (used by the test builder and the
    model writer)."""
    a = np.asarray(a)
    rev = {np.dtype(v): k for k, v in _NP_OF_DT.items()}
    if a.dtype not in rev:
        raise ValueError(f"unsupported numpy dtype {a.dtype}")
    t = OIR.TensorProto(name=name, data_type=rev[a.dtype],
                        dims=list(a.shape), raw_data=a.tobytes())
    return t


# --------------------------------------------------------------------------


class _Ctx:
    """Per-node mapper context (attr access, resolved inputs, static
    values, shape inference) — the `_Ctx` shape from tf_graph_mapper."""

    def __init__(self, imp: "_Importer", node: "OIR.NodeProto"):
        self.imp = imp
        self.node = node
        self.sd = imp.sd
        self.name = node.name or (node.output[0] if node.output else "?")
        # ONNX marks omitted optional inputs with ""
        self.data_inputs = list(node.input)
        self.opset = imp.opset

    # --- attrs ---------------------------------------------------------
    def attr(self, name: str, default=None):
        for a in self.node.attribute:
            if a.name != name:
                continue
            T = OIR.AttributeProto
            if a.type == T.FLOAT:
                return float(a.f)
            if a.type == T.INT:
                return int(a.i)
            if a.type == T.STRING:
                return a.s.decode()
            if a.type == T.TENSOR:
                return tensor_to_numpy(a.t)
            if a.type == T.FLOATS:
                return [float(v) for v in a.floats]
            if a.type == T.INTS:
                return [int(v) for v in a.ints]
            if a.type == T.STRINGS:
                return [v.decode() for v in a.strings]
            raise ValueError(f"attr {name!r}: unsupported type {a.type}")
        return default

    # --- inputs --------------------------------------------------------
    def n_in(self) -> int:
        return len(self.data_inputs)

    def has_input(self, i: int) -> bool:
        return i < len(self.data_inputs) and self.data_inputs[i] != ""

    def var(self, i: int) -> SDVariable:
        return self.imp.resolve_var(self.data_inputs[i])

    def var_or_none(self, i: int) -> Optional[SDVariable]:
        return self.var(i) if self.has_input(i) else None

    def vars(self, start: int = 0, end: Optional[int] = None):
        return [self.imp.resolve_var(t)
                for t in self.data_inputs[start:end] if t != ""]

    def static(self, i: int) -> np.ndarray:
        t = self.data_inputs[i]
        v = self.imp.static_value(t)
        if v is None:
            raise ValueError(
                f"input {i} ({t!r}) of node {self.name!r} "
                f"({self.node.op_type}) must be statically resolvable "
                "(initializer/constant/folded subgraph); dynamic values are "
                "not supported for structural arguments under XLA's "
                "static-shape model")
        return v

    def static_or_none(self, i: int) -> Optional[np.ndarray]:
        if not self.has_input(i):
            return None
        return self.imp.static_value(self.data_inputs[i])

    def axes_arg(self, attr_name: str = "axes", input_idx: int = 1,
                 default=None):
        """opset≥13 moved several ``axes`` from attribute to input; accept
        both."""
        v = self.attr(attr_name)
        if v is not None:
            return [int(a) for a in v]
        s = self.static_or_none(input_idx)
        if s is not None:
            return [int(a) for a in np.atleast_1d(s)]
        return default

    def shape_of_input(self, i: int) -> Tuple[int, ...]:
        return self.imp.infer_shape(self.data_inputs[i])

    def dtype_of_input(self, i: int) -> np.dtype:
        return self.imp.infer_dtype(self.data_inputs[i])

    def emit(self, op_name: str, inputs: Sequence[Any], n_outputs=None, **kw):
        return self.sd._add_op(op_name, list(inputs),
                               name=self.name.replace(":", "_"),
                               n_outputs=n_outputs, **kw)


# --------------------------------------------------------------------------


class _Importer:
    def __init__(self, model: "OIR.ModelProto",
                 input_shapes: Optional[Dict[str, Sequence[int]]] = None):
        self.model = model
        self.g = model.graph
        self.sd = SameDiff.create()
        self.input_shapes = dict(input_shapes or {})
        self.opset = 13
        for osi in model.opset_import:
            if osi.domain in ("", "ai.onnx"):
                self.opset = int(osi.version)
        self._env: Dict[str, SDVariable] = {}
        self._static: Dict[str, np.ndarray] = {}
        self._shape_cache: Dict[str, Tuple[int, ...]] = {}
        self.placeholders: List[str] = []
        self.outputs: List[str] = []

    # --- name plumbing --------------------------------------------------
    def _bind(self, node: "OIR.NodeProto", outs) -> None:
        if isinstance(outs, SDVariable):
            outs = (outs,)
        for tname, v in zip(node.output, outs):
            if tname:
                self._env[tname] = v

    def resolve_var(self, tensor_name: str) -> SDVariable:
        if tensor_name in self._env:
            return self._env[tensor_name]
        sval = self._static.get(tensor_name)
        if sval is not None:
            v = self.sd.constant(_safe(tensor_name), sval)
            self._env[tensor_name] = v
            return v
        raise KeyError(f"unresolved ONNX tensor {tensor_name!r}")

    def static_value(self, tensor_name: str) -> Optional[np.ndarray]:
        return self._static.get(tensor_name)

    # --- shape/dtype inference over the partial graph -------------------
    def infer_shape(self, tensor_name: str) -> Tuple[int, ...]:
        import jax

        if tensor_name in self._shape_cache:
            return self._shape_cache[tensor_name]
        sval = self._static.get(tensor_name)
        if sval is not None:
            return tuple(np.asarray(sval).shape)
        var = self.resolve_var(tensor_name)
        vinfo = self.sd._vars[var.name]
        if vinfo.shape is not None and all(d is not None for d in vinfo.shape):
            shp = tuple(int(d) for d in vinfo.shape)
            self._shape_cache[tensor_name] = shp
            return shp
        return tuple(int(d) for d in self._eval_struct(tensor_name).shape)

    def infer_dtype(self, tensor_name: str) -> np.dtype:
        """True result dtype via abstract tracing (the `_Var.dtype` field is
        only authoritative for placeholders/constants — op outputs default
        to float32 there)."""
        sval = self._static.get(tensor_name)
        if sval is not None:
            return np.asarray(sval).dtype
        var = self.resolve_var(tensor_name)
        vinfo = self.sd._vars[var.name]
        if vinfo.value is not None:
            return np.asarray(vinfo.value).dtype
        if vinfo.producer is None:   # placeholder: declared dtype holds
            return np.dtype(vinfo.dtype)
        return np.dtype(self._eval_struct(tensor_name).dtype)

    def _eval_struct(self, tensor_name: str):
        """Abstract-eval the partial graph up to ``tensor_name`` and return
        its jax.ShapeDtypeStruct (also fills the shape cache)."""
        import jax

        var = self.resolve_var(tensor_name)
        fn = self.sd._make_fn((var.name,), training=False)
        params = {n: jax.ShapeDtypeStruct(np.asarray(v.value).shape,
                                          np.asarray(v.value).dtype)
                  for n, v in self.sd._vars.items()
                  if v.vtype == "VARIABLE"}
        ph = {}
        for n in self.sd.placeholders():
            pshape = self.sd._vars[n].shape
            if pshape is None or any(d is None for d in pshape):
                raise ValueError(
                    f"cannot infer shape of {tensor_name!r}: placeholder "
                    f"{n!r} has unknown dims — pass input_shapes={{...}} to "
                    "the importer")
            pdt = np.dtype(self.sd._vars[n].dtype)
            ph[n] = jax.ShapeDtypeStruct(tuple(pshape), pdt)
        key_struct = jax.ShapeDtypeStruct((2,), np.uint32)
        out = jax.eval_shape(fn, params, ph, key_struct)
        self._shape_cache[tensor_name] = tuple(int(d) for d in out[0].shape)
        return out[0]

    # --- main loop ------------------------------------------------------
    def run(self) -> SameDiff:
        # initializers → static pool (materialized as graph constants only
        # when consumed as tensors, exactly like TF Const nodes)
        init_names = set()
        for t in self.g.initializer:
            self._static[t.name] = tensor_to_numpy(t)
            init_names.add(t.name)

        for vi in self.g.input:
            if vi.name in init_names:
                continue
            self._import_placeholder(vi)

        for node in self.g.node:
            opn = node.op_type
            if opn == "Constant":
                val = self._constant_value(node)
                self._static[node.output[0]] = val
                continue
            ctx = _Ctx(self, node)
            if opn == "Shape":
                shp = np.asarray(self.infer_shape(node.input[0]), np.int64)
                start = ctx.attr("start", 0) or 0
                end = ctx.attr("end")
                shp = shp[start:end if end is not None else len(shp)]
                self._static[node.output[0]] = shp
                continue
            if opn == "Size":
                shp = self.infer_shape(node.input[0])
                self._static[node.output[0]] = np.asarray(
                    int(np.prod(shp, dtype=np.int64)), np.int64)
                continue
            folder = _FOLDERS.get(opn)
            if folder is not None:
                statics = [self._static.get(t) if t else None
                           for t in node.input]
                if all(t == "" or s is not None
                       for t, s in zip(node.input, statics)):
                    try:
                        res = folder(ctx, statics)
                    except Exception:
                        res = None
                    if res is not None:
                        if not isinstance(res, (list, tuple)):
                            res = (res,)
                        for tname, r in zip(node.output, res):
                            self._static[tname] = np.asarray(r)
                        continue
            mapper = _ONNX_OPS.get(opn)
            if mapper is None:
                raise UnsupportedOnnxOpError(opn, ctx.name)
            outs = mapper(ctx)
            if outs is not None:
                self._bind(node, outs)

        for vi in self.g.output:
            if vi.name in self._env:
                self.outputs.append(self._env[vi.name].name)
            elif vi.name in self._static:
                self.outputs.append(self.resolve_var(vi.name).name)
        return self.sd

    def _import_placeholder(self, vi: "OIR.ValueInfoProto") -> None:
        tt = vi.type.tensor_type
        shape: Optional[List[Optional[int]]] = None
        if tt.HasField("shape"):
            shape = []
            for d in tt.shape.dim:
                if d.WhichOneof("value") == "dim_value":
                    shape.append(int(d.dim_value))
                else:
                    shape.append(None)
        if vi.name in self.input_shapes:
            shape = list(self.input_shapes[vi.name])
        dt = np.dtype(_NP_OF_DT.get(tt.elem_type, np.float32))
        v = self.sd.placeholder(_safe(vi.name), shape=shape, dtype=dt.name)
        self._env[vi.name] = v
        self.placeholders.append(v.name)

    @staticmethod
    def _constant_value(node: "OIR.NodeProto") -> np.ndarray:
        for a in node.attribute:
            if a.name == "value":
                return tensor_to_numpy(a.t)
            if a.name == "value_float":
                return np.asarray(a.f, np.float32)
            if a.name == "value_int":
                return np.asarray(a.i, np.int64)
            if a.name == "value_floats":
                return np.asarray(list(a.floats), np.float32)
            if a.name == "value_ints":
                return np.asarray(list(a.ints), np.int64)
        raise ValueError(f"Constant node {node.name!r} without value")


def _safe(name: str) -> str:
    return name.replace(":", "_").replace("/", "_").replace(".", "_")


# --------------------------------------------------------------------------
# static folders (structural subgraph evaluation, numpy semantics)


def _fold_slice(ctx, s):
    starts = ctx.attr("starts") or np.atleast_1d(s[1]).tolist()
    ends = ctx.attr("ends") or np.atleast_1d(s[2]).tolist()
    axes = ctx.axes_arg("axes", 3, list(range(len(starts))))
    steps = ([1] * len(starts) if ctx.n_in() < 5 or s[4] is None
             else np.atleast_1d(s[4]).tolist())
    sl = [slice(None)] * np.ndim(s[0])
    for a, st, en, sp in zip(axes, starts, ends, steps):
        sl[a] = slice(int(st), int(en), int(sp))
    return np.asarray(s[0])[tuple(sl)]


_FOLDERS: Dict[str, Callable] = {
    "Cast": lambda ctx, s: np.asarray(s[0]).astype(
        _NP_OF_DT[ctx.attr("to", _DT.FLOAT)]),
    "Gather": lambda ctx, s: np.take(s[0], np.asarray(s[1], np.int64),
                                     axis=ctx.attr("axis", 0)),
    "Concat": lambda ctx, s: np.concatenate(
        [np.atleast_1d(v) for v in s], axis=ctx.attr("axis", 0)),
    "Unsqueeze": lambda ctx, s: np.expand_dims(
        s[0], tuple(ctx.axes_arg("axes", 1))),
    "Squeeze": lambda ctx, s: np.squeeze(
        s[0], tuple(ctx.axes_arg("axes", 1, default=None) or ())) \
        if ctx.axes_arg("axes", 1, default=None) else np.squeeze(s[0]),
    "Slice": _fold_slice,
    "Add": lambda ctx, s: np.add(s[0], s[1]),
    "Sub": lambda ctx, s: np.subtract(s[0], s[1]),
    "Mul": lambda ctx, s: np.multiply(s[0], s[1]),
    # ONNX integer Div truncates toward zero (C semantics), not floor;
    # computed exactly in integer arithmetic (no float round-trip, so
    # int64 values beyond 2^53 fold correctly)
    "Div": lambda ctx, s: (_int_trunc_divide(s[0], s[1])
                           if np.issubdtype(np.asarray(s[0]).dtype,
                                            np.integer)
                           else np.divide(s[0], s[1])),
    "Reshape": lambda ctx, s: _np_reshape_onnx(s[0], s[1]),
    "Transpose": lambda ctx, s: np.transpose(
        s[0], ctx.attr("perm") or None),
    "Range": lambda ctx, s: np.arange(
        np.asarray(s[0]).item(), np.asarray(s[1]).item(),
        np.asarray(s[2]).item()).astype(np.asarray(s[0]).dtype),
    "ConstantOfShape": lambda ctx, s: np.full(
        np.asarray(s[0], np.int64).tolist(),
        ctx.attr("value", np.zeros(1, np.float32))[0]),
    "ReduceProd": lambda ctx, s: np.prod(
        s[0], axis=tuple(ctx.axes_arg("axes", 1, None) or ()) or None,
        keepdims=bool(ctx.attr("keepdims", 1))),
    "Identity": lambda ctx, s: np.asarray(s[0]),
    "Equal": lambda ctx, s: np.equal(s[0], s[1]),
    "Where": lambda ctx, s: np.where(s[0], s[1], s[2]),
    "Expand": lambda ctx, s: np.broadcast_to(
        s[0], np.broadcast_shapes(np.shape(s[0]),
                                  tuple(np.asarray(s[1], np.int64)))),
}


def _int_trunc_divide(a, b):
    """Exact integer division truncating toward zero (C semantics)."""
    a, b = np.asarray(a), np.asarray(b)
    q = np.floor_divide(np.abs(a), np.abs(b))
    neg = (a < 0) ^ (b < 0)
    return np.where(neg, -q, q).astype(a.dtype)


def _np_reshape_onnx(x, shape):
    x = np.asarray(x)
    shape = [int(d) for d in np.asarray(shape, np.int64)]
    # ONNX: 0 = copy input dim (unless allowzero), -1 = infer
    shape = [x.shape[i] if d == 0 else d for i, d in enumerate(shape)]
    return x.reshape(shape)


# --------------------------------------------------------------------------
# mappers — elementwise


def _binary(op_name):
    def m(ctx: _Ctx):
        return ctx.emit(op_name, [ctx.var(0), ctx.var(1)])

    return m


_BINARY = {
    "Add": "add", "Sub": "subtract", "Mul": "multiply",
    "Pow": "pow",
    "Equal": "equals", "Greater": "greater", "GreaterOrEqual": "greater_equal",
    "Less": "less", "LessOrEqual": "less_equal",
    "And": "boolean_and", "Or": "boolean_or", "Xor": "boolean_xor",
}
for _onnx_name, _our in _BINARY.items():
    onnx_op(_onnx_name)(_binary(_our))


@onnx_op("Div")
def _div(ctx):
    # ONNX Div truncates toward zero on integers (C semantics); floats are
    # true division. The registry has exact ops for both.
    if np.issubdtype(ctx.dtype_of_input(0), np.integer) \
            and np.issubdtype(ctx.dtype_of_input(1), np.integer):
        return ctx.emit("truncatediv", [ctx.var(0), ctx.var(1)])
    return ctx.emit("divide", [ctx.var(0), ctx.var(1)])


@onnx_op("Mod")
def _mod(ctx):
    if not ctx.attr("fmod", 0):
        # fmod=0: Python/floor semantics (integer inputs per spec)
        return ctx.emit("floormod", [ctx.var(0), ctx.var(1)])
    # fmod=1: C-style truncated remainder (sign follows the dividend) —
    # exactly the registry "mod" op (jnp.fmod), dtype-preserving
    return ctx.emit("mod", [ctx.var(0), ctx.var(1)])


def _unary(op_name, **fixed_kw):
    def m(ctx: _Ctx):
        return ctx.emit(op_name, [ctx.var(0)], **fixed_kw)

    return m


_UNARY = {
    "Abs": "abs", "Neg": "neg", "Exp": "exp", "Log": "log", "Sqrt": "sqrt",
    "Reciprocal": "reciprocal", "Floor": "floor", "Ceil": "ceil",
    "Round": "round", "Sign": "sign", "Sin": "sin", "Cos": "cos",
    "Tan": "tan", "Asin": "asin", "Acos": "acos", "Atan": "atan",
    "Sinh": "sinh", "Cosh": "cosh", "Tanh": "tanh", "Asinh": "asinh",
    "Acosh": "acosh", "Atanh": "atanh", "Erf": "erf", "Sigmoid": "sigmoid",
    "Relu": "relu", "Softplus": "softplus", "Softsign": "softsign",
    "Not": "boolean_not", "Identity": "identity", "Mish": "mish",
    "IsNaN": "isnan", "IsInf": "isinf",
}
for _onnx_name, _our in _UNARY.items():
    onnx_op(_onnx_name)(_unary(_our))


@onnx_op("LeakyRelu")
def _leaky_relu(ctx):
    return ctx.emit("leakyrelu", [ctx.var(0)], alpha=ctx.attr("alpha", 0.01))


@onnx_op("Elu")
def _elu(ctx):
    a = ctx.attr("alpha", 1.0)
    out = ctx.emit("elu", [ctx.var(0)])
    if a != 1.0:
        # ONNX Elu scales only the negative branch
        x = ctx.var(0)
        neg = ctx.sd._add_op("minimum", [x, 0.0])
        em1 = ctx.sd._add_op("expm1", [neg])
        pos = ctx.sd._add_op("relu", [x])
        scaled = ctx.sd._add_op("multiply", [em1, float(a)])
        return ctx.sd._add_op("add", [pos, scaled], name=ctx.name + "_elu")
    return out


@onnx_op("Selu")
def _selu(ctx):
    return ctx.emit("selu", [ctx.var(0)])


@onnx_op("PRelu")
def _prelu(ctx):
    return ctx.emit("prelu", [ctx.var(0), ctx.var(1)])


@onnx_op("ThresholdedRelu")
def _thresholded_relu(ctx):
    return ctx.emit("thresholdedrelu", [ctx.var(0)],
                    theta=ctx.attr("alpha", 1.0))


@onnx_op("HardSigmoid")
def _hard_sigmoid(ctx):
    a, b = ctx.attr("alpha", 0.2), ctx.attr("beta", 0.5)
    x = ctx.var(0)
    lin = ctx.sd._add_op("add", [ctx.sd._add_op("multiply", [x, float(a)]),
                                 float(b)])
    return ctx.emit("clip_by_value", [lin], clip_min=0.0, clip_max=1.0)


@onnx_op("Gelu")
def _gelu(ctx):
    approx = ctx.attr("approximate", "none")
    return ctx.emit("gelu" if approx == "tanh" else "gelu_exact",
                    [ctx.var(0)])


@onnx_op("Clip")
def _clip(ctx):
    if ctx.opset >= 11:
        # distinguish "input omitted" (unbounded) from "present but
        # dynamic" (ctx.static raises the actionable error)
        lo = float(ctx.static(1)) if ctx.has_input(1) else -np.inf
        hi = float(ctx.static(2)) if ctx.has_input(2) else np.inf
    else:
        lo = float(ctx.attr("min", -np.inf))
        hi = float(ctx.attr("max", np.inf))
    return ctx.emit("clip_by_value", [ctx.var(0)], clip_min=lo, clip_max=hi)


@onnx_op("Cast")
def _cast(ctx):
    dst = np.dtype(_NP_OF_DT[ctx.attr("to")])
    return ctx.emit("cast", [ctx.var(0)], dtype=dst.name)


@onnx_op("Where")
def _where(ctx):
    return ctx.emit("select", [ctx.var(0), ctx.var(1), ctx.var(2)])


def _variadic(op_name, fold2):
    """ONNX Min/Max/Sum/Mean take N inputs; reduce pairwise."""

    def m(ctx: _Ctx):
        vs = ctx.vars()
        out = vs[0]
        for v in vs[1:]:
            out = ctx.sd._add_op(fold2, [out, v])
        if op_name == "Mean":
            out = ctx.sd._add_op("divide", [out, float(len(vs))])
        return ctx.sd._add_op("identity", [out], name=ctx.name + "_out")

    return m


onnx_op("Min")(_variadic("Min", "minimum"))
onnx_op("Max")(_variadic("Max", "maximum"))
onnx_op("Sum")(_variadic("Sum", "add"))
onnx_op("Mean")(_variadic("Mean", "add"))


# --------------------------------------------------------------------------
# mappers — reductions

_REDUCE = {"ReduceSum": "reduce_sum", "ReduceMean": "reduce_mean",
           "ReduceMax": "reduce_max", "ReduceMin": "reduce_min",
           "ReduceProd": "reduce_prod", "ReduceL1": "reduce_norm1",
           "ReduceL2": "reduce_norm2", "ReduceLogSumExp": "reduce_logsumexp"}


def _reduction(op_name):
    def m(ctx: _Ctx):
        axes = ctx.axes_arg("axes", 1, default=None)
        keep = bool(ctx.attr("keepdims", 1))
        if axes is None and ctx.attr("noop_with_empty_axes", 0):
            return ctx.emit("identity", [ctx.var(0)])
        return ctx.emit(op_name, [ctx.var(0)],
                        dims=tuple(axes) if axes is not None else None,
                        keep_dims=keep)

    return m


for _onnx_name, _our in _REDUCE.items():
    onnx_op(_onnx_name)(_reduction(_our))


@onnx_op("ArgMax")
def _argmax(ctx):
    out = ctx.emit("argmax", [ctx.var(0)], dims=(ctx.attr("axis", 0),),
                   keep_dims=bool(ctx.attr("keepdims", 1)))
    return ctx.sd._add_op("cast", [out], dtype="int64", name=ctx.name + "_i64")


@onnx_op("ArgMin")
def _argmin(ctx):
    out = ctx.emit("argmin", [ctx.var(0)], dims=(ctx.attr("axis", 0),),
                   keep_dims=bool(ctx.attr("keepdims", 1)))
    return ctx.sd._add_op("cast", [out], dtype="int64", name=ctx.name + "_i64")


@onnx_op("CumSum")
def _cumsum(ctx):
    axis = int(ctx.static(1))
    return ctx.emit("cumsum", [ctx.var(0)], axis=axis,
                    exclusive=bool(ctx.attr("exclusive", 0)),
                    reverse=bool(ctx.attr("reverse", 0)))


@onnx_op("TopK")
def _topk(ctx):
    k = int(np.atleast_1d(ctx.static(1))[0])
    vals, idx = ctx.emit("top_k", [ctx.var(0)], k=k,
                         sorted=bool(ctx.attr("sorted", 1)), n_outputs=2)
    idx64 = ctx.sd._add_op("cast", [idx], dtype="int64",
                           name=ctx.name + "_i64")
    return (vals, idx64)


# --------------------------------------------------------------------------
# mappers — shape/structure


@onnx_op("Reshape")
def _reshape(ctx):
    shape = [int(d) for d in np.asarray(ctx.static(1), np.int64)]
    in_shape = ctx.shape_of_input(0)
    shape = [in_shape[i] if d == 0 else d for i, d in enumerate(shape)]
    return ctx.emit("reshape", [ctx.var(0)], shape=tuple(shape))


@onnx_op("Resize", "Upsample")
def _resize(ctx):
    """ONNX Resize (opset 10+: X, roi, scales, sizes) and the deprecated
    Upsample (X, scales) — the CNN upsampling staple (round 5). Static
    scales/sizes only (XLA static shapes); NCHW in the graph, resized
    through the NHWC registry ops with a permute pair XLA fuses away."""
    mode = ctx.attr("mode", "nearest")
    ct = ctx.attr("coordinate_transformation_mode", "half_pixel")
    shp = ctx.shape_of_input(0)
    if len(shp) != 4:
        raise UnsupportedOnnxOpError(
            f"{ctx.node.op_type}: rank-{len(shp)} input (NCHW images only)",
            ctx.name)
    n, c, h, w = (int(d) for d in shp)
    sizes = None
    if ctx.node.op_type == "Upsample":
        scales = np.asarray(ctx.static(1)).reshape(-1)
    else:
        sizes_in = (ctx.static_or_none(3) if ctx.n_in() > 3 else None)
        scales_in = (ctx.static_or_none(2) if ctx.n_in() > 2 else None)
        if sizes_in is not None and np.asarray(sizes_in).size:
            sizes = np.asarray(sizes_in).reshape(-1)
            scales = None
        elif scales_in is not None and np.asarray(scales_in).size:
            scales = np.asarray(scales_in).reshape(-1)
        else:
            raise UnsupportedOnnxOpError(
                "Resize: scales/sizes must be static initializers",
                ctx.name)
    if sizes is not None:
        oh, ow = int(sizes[2]), int(sizes[3])
    else:
        if not (abs(scales[0] - 1) < 1e-6 and abs(scales[1] - 1) < 1e-6):
            raise UnsupportedOnnxOpError(
                f"{ctx.node.op_type}: batch/channel scaling", ctx.name)
        oh, ow = int(round(h * float(scales[2]))), \
            int(round(w * float(scales[3])))
    if ct == "align_corners":
        ac, hp = True, False
    elif ct in ("half_pixel", "pytorch_half_pixel"):
        ac, hp = False, True
    elif ct in ("asymmetric", "tf_crop_and_resize"):
        if ct == "tf_crop_and_resize":
            raise UnsupportedOnnxOpError("Resize(tf_crop_and_resize)",
                                         ctx.name)
        ac, hp = False, False
    else:
        raise UnsupportedOnnxOpError(
            f"Resize(coordinate_transformation_mode={ct!r})", ctx.name)
    mode = mode.decode() if isinstance(mode, bytes) else mode
    nhwc = ctx.sd._add_op("permute", [ctx.var(0)], dims=(0, 2, 3, 1))
    if mode == "nearest":
        nm = ctx.attr("nearest_mode", "round_prefer_floor")
        nm = nm.decode() if isinstance(nm, bytes) else nm
        # the classic Upsample contract is asymmetric+floor — the exact
        # integer-scale case every CNN decoder uses; reject samplings the
        # registry op does not implement rather than import approximately
        if hp and nm not in ("round_prefer_floor", "floor"):
            raise UnsupportedOnnxOpError(
                f"Resize(nearest, nearest_mode={nm!r})", ctx.name)
        # asymmetric samples floor(i*scale) exactly (ops/image
        # resize_nearest with half_pixel_centers=False). The spec-default
        # round_prefer_floor equals floor iff every sampled coordinate
        # i*in/out has fractional part <= 1/2 (ties prefer floor) — true
        # for the classic 2x/integer-downscale cases; gate on that exact
        # rational test and refuse only genuinely divergent samplings
        # instead of silently shifting the image
        if not hp and not ac and nm != "floor":
            def _rpf_equals_floor(in_sz, out_sz):
                return all(2 * ((i * in_sz) % out_sz) <= out_sz
                           for i in range(out_sz))

            if not (nm == "round_prefer_floor"
                    and _rpf_equals_floor(h, oh)
                    and _rpf_equals_floor(w, ow)):
                raise UnsupportedOnnxOpError(
                    f"Resize(nearest, coordinate_transformation_mode="
                    f"'asymmetric', nearest_mode={nm!r}) at {h}x{w}->"
                    f"{oh}x{ow} — the asymmetric path implements floor "
                    f"sampling, which differs here", ctx.name)
        out = ctx.sd._add_op("resize_nearest", [nhwc], height=oh, width=ow,
                             align_corners=ac, half_pixel_centers=hp)
    elif mode == "linear":
        out = ctx.sd._add_op("resize_bilinear", [nhwc], height=oh,
                             width=ow, align_corners=ac,
                             half_pixel_centers=hp)
    elif mode == "cubic":
        if ac or not hp:
            raise UnsupportedOnnxOpError(
                "Resize(cubic) supports half_pixel only", ctx.name)
        out = ctx.sd._add_op("resize_bicubic", [nhwc], height=oh, width=ow)
    else:
        raise UnsupportedOnnxOpError(f"Resize(mode={mode!r})", ctx.name)
    return ctx.emit("permute", [out], dims=(0, 3, 1, 2))


@onnx_op("Transpose")
def _transpose(ctx):
    perm = ctx.attr("perm")
    if perm is None:
        perm = list(range(len(ctx.shape_of_input(0))))[::-1]
    return ctx.emit("permute", [ctx.var(0)], dims=tuple(perm))


@onnx_op("Concat")
def _concat(ctx):
    return ctx.sd._add_op("concat", ctx.vars(), name=_safe(ctx.name),
                          axis=ctx.attr("axis", 0))


@onnx_op("Split")
def _split(ctx):
    axis = ctx.attr("axis", 0)
    sizes = ctx.attr("split")
    if sizes is None and ctx.has_input(1):
        sizes = [int(v) for v in np.atleast_1d(ctx.static(1))]
    n_out = len(ctx.node.output)
    if sizes is None:
        return ctx.emit("split", [ctx.var(0)], num_split=n_out, axis=axis,
                        n_outputs=n_out)
    return ctx.emit("split_v", [ctx.var(0)], sizes=tuple(sizes), axis=axis,
                    n_outputs=n_out)


@onnx_op("Squeeze")
def _squeeze(ctx):
    axes = ctx.axes_arg("axes", 1, default=None)
    return ctx.emit("squeeze", [ctx.var(0)],
                    axis=tuple(axes) if axes else None)


@onnx_op("Unsqueeze")
def _unsqueeze(ctx):
    axes = sorted(ctx.axes_arg("axes", 1))
    v = ctx.var(0)
    for i, a in enumerate(axes):
        v = ctx.sd._add_op("expand_dims", [v], axis=int(a),
                           name=f"{_safe(ctx.name)}_u{i}")
    return v


@onnx_op("Flatten")
def _flatten(ctx):
    shp = ctx.shape_of_input(0)
    axis = _norm_axis_incl(ctx.attr("axis", 1), len(shp)) if shp else 0
    lead = int(np.prod(shp[:axis], dtype=np.int64)) if axis > 0 else 1
    if axis == len(shp) and shp:
        # spec-legal axis==rank: everything into dim 0 → [prod, 1]
        return ctx.emit("reshape", [ctx.var(0)], shape=(lead, 1))
    return ctx.emit("reshape", [ctx.var(0)], shape=(lead, -1))


def _norm_axis_incl(axis: int, rank: int) -> int:
    """Normalize an ONNX coerce-to-2D axis where axis==rank is legal
    (Flatten, opset<13 Softmax): only negatives wrap."""
    a = axis + rank if axis < 0 else axis
    if not 0 <= a <= rank:
        raise ValueError(f"axis {axis} out of range for rank {rank}")
    return a


@onnx_op("Gather")
def _gather(ctx):
    idx = ctx.static_or_none(1)
    if idx is not None:
        return ctx.emit("gather", [ctx.var(0), idx.astype(np.int32)],
                        axis=ctx.attr("axis", 0))
    return ctx.emit("gather", [ctx.var(0), ctx.var(1)],
                    axis=ctx.attr("axis", 0))


@onnx_op("GatherND")
def _gather_nd(ctx):
    if ctx.attr("batch_dims", 0):
        raise UnsupportedOnnxOpError("GatherND(batch_dims>0)", ctx.name)
    return ctx.emit("gather_nd", [ctx.var(0), ctx.var(1)])


@onnx_op("Slice")
def _slice(ctx):
    if ctx.opset >= 10:
        starts = [int(v) for v in np.atleast_1d(ctx.static(1))]
        ends = [int(v) for v in np.atleast_1d(ctx.static(2))]
        axes = ctx.axes_arg("axes", 3, list(range(len(starts))))
        steps = ([1] * len(starts) if not ctx.has_input(4)
                 else [int(v) for v in np.atleast_1d(ctx.static(4))])
    else:
        starts = ctx.attr("starts")
        ends = ctx.attr("ends")
        axes = ctx.attr("axes", list(range(len(starts))))
        steps = [1] * len(starts)
    shp = ctx.shape_of_input(0)
    begin = [0] * len(shp)
    end = [int(d) for d in shp]
    stride = [1] * len(shp)
    for a, st, en, sp in zip(axes, starts, ends, steps):
        a = a % len(shp)
        d = shp[a]
        st = max(st + d, 0) if st < 0 else min(st, d)
        en = max(en + d, -1) if en < 0 else min(en, d)
        begin[a], end[a], stride[a] = st, en, sp
    return ctx.emit("strided_slice", [ctx.var(0)], begin=tuple(begin),
                    end=tuple(end), strides=tuple(stride))


@onnx_op("Expand")
def _expand(ctx):
    target = [int(d) for d in np.asarray(ctx.static(1), np.int64)]
    in_shape = ctx.shape_of_input(0)
    shape = list(np.broadcast_shapes(tuple(in_shape), tuple(target)))
    return ctx.emit("broadcast_to", [ctx.var(0)], shape=tuple(shape))


@onnx_op("Tile")
def _tile(ctx):
    reps = [int(v) for v in np.asarray(ctx.static(1), np.int64)]
    return ctx.emit("tile", [ctx.var(0)], reps=tuple(reps))


@onnx_op("Pad")
def _pad(ctx):
    mode = ctx.attr("mode", "constant")
    if ctx.opset >= 11:
        pads = [int(v) for v in np.atleast_1d(ctx.static(1))]
        cval = ctx.static_or_none(2)
        cval = float(np.atleast_1d(cval)[0]) if cval is not None else 0.0
    else:
        pads = ctx.attr("pads")
        cval = ctx.attr("value", 0.0)
    n = len(pads) // 2
    paddings = tuple((pads[i], pads[n + i]) for i in range(n))
    mode_map = {"constant": "constant", "reflect": "reflect",
                "edge": "edge"}
    if mode not in mode_map:
        raise UnsupportedOnnxOpError(f"Pad(mode={mode})", ctx.name)
    return ctx.emit("pad", [ctx.var(0)], paddings=paddings,
                    mode=mode_map[mode], constant_value=cval)


@onnx_op("Range")
def _range(ctx):
    return ctx.emit("range", [float(np.atleast_1d(ctx.static(0))[0]),
                              float(np.atleast_1d(ctx.static(1))[0]),
                              float(np.atleast_1d(ctx.static(2))[0])])


@onnx_op("OneHot")
def _one_hot(ctx):
    depth = int(np.atleast_1d(ctx.static(1))[0])
    values = ctx.static_or_none(2)
    off, on = (0.0, 1.0) if values is None else (float(values[0]),
                                                float(values[1]))
    return ctx.emit("one_hot", [ctx.var(0)], depth=depth, on_value=on,
                    off_value=off, axis=ctx.attr("axis", -1))


@onnx_op("Dropout")
def _dropout(ctx):
    # inference import: identity (mask output unused in frozen inference
    # graphs; training uses this framework's own dropout)
    return ctx.emit("identity", [ctx.var(0)])


# --------------------------------------------------------------------------
# mappers — linear algebra / NN


@onnx_op("MatMul")
def _matmul(ctx):
    a_shape = ctx.shape_of_input(0)
    b_shape = ctx.shape_of_input(1)
    if len(a_shape) > 2 or len(b_shape) > 2:
        return ctx.emit("batched_gemm", [ctx.var(0), ctx.var(1)])
    return ctx.emit("matmul", [ctx.var(0), ctx.var(1)])


@onnx_op("Gemm")
def _gemm(ctx):
    alpha = ctx.attr("alpha", 1.0)
    beta = ctx.attr("beta", 1.0)
    out = ctx.sd._add_op("matmul", [ctx.var(0), ctx.var(1)],
                         transpose_x=bool(ctx.attr("transA", 0)),
                         transpose_y=bool(ctx.attr("transB", 0)))
    if alpha != 1.0:
        out = ctx.sd._add_op("multiply", [out, float(alpha)])
    if ctx.has_input(2):
        c = ctx.var(2)
        if beta != 1.0:
            c = ctx.sd._add_op("multiply", [c, float(beta)])
        out = ctx.sd._add_op("add", [out, c])
    return ctx.sd._add_op("identity", [out], name=_safe(ctx.name) + "_out")


@onnx_op("Einsum")
def _einsum(ctx):
    return ctx.sd._add_op("einsum", ctx.vars(), name=_safe(ctx.name),
                          equation=ctx.attr("equation"))


@onnx_op("Softmax")
def _softmax(ctx):
    if ctx.opset >= 13:
        return ctx.emit("softmax", [ctx.var(0)], axis=ctx.attr("axis", -1))
    # opset<13: softmax over the flattened trailing dims [axis:]
    shp = ctx.shape_of_input(0)
    axis = _norm_axis_incl(ctx.attr("axis", 1), len(shp)) if shp else 0
    lead = int(np.prod(shp[:axis], dtype=np.int64)) if axis > 0 else 1
    # axis==rank flattens to [prod, 1]; softmax over one element is 1.0,
    # which the (lead, -1) reshape realizes naturally
    flat = ctx.sd._add_op("reshape", [ctx.var(0)], shape=(lead, -1))
    sm = ctx.sd._add_op("softmax", [flat], axis=-1)
    return ctx.emit("reshape", [sm], shape=tuple(shp))


@onnx_op("LogSoftmax")
def _log_softmax(ctx):
    if ctx.opset >= 13:
        return ctx.emit("log_softmax", [ctx.var(0)],
                        axis=ctx.attr("axis", -1))
    shp = ctx.shape_of_input(0)
    axis = _norm_axis_incl(ctx.attr("axis", 1), len(shp)) if shp else 0
    lead = int(np.prod(shp[:axis], dtype=np.int64)) if axis > 0 else 1
    flat = ctx.sd._add_op("reshape", [ctx.var(0)], shape=(lead, -1))
    sm = ctx.sd._add_op("log_softmax", [flat], axis=-1)
    return ctx.emit("reshape", [sm], shape=tuple(shp))


def _conv_pads(ctx, rank=2, kernel=None, strides=None, dilations=None):
    """Resolve ONNX padding to (symmetric_pads, explicit_begin_end): one of
    the two is None. ``symmetric_pads`` may also be the string "SAME"."""
    auto = ctx.attr("auto_pad", "NOTSET")
    if auto == "SAME_UPPER":
        return "SAME", None       # XLA "SAME" IS SAME_UPPER
    if auto == "SAME_LOWER":
        # extra padding pixel goes at the BEGINNING — compute explicit pads
        shp = ctx.shape_of_input(0)[2:]
        strides = strides or (1,) * rank
        dilations = dilations or (1,) * rank
        begin, end = [], []
        for i in range(rank):
            eff = (kernel[i] - 1) * dilations[i] + 1
            out = -(-shp[i] // strides[i])
            total = max((out - 1) * strides[i] + eff - shp[i], 0)
            b = total - total // 2
            begin.append(b)
            end.append(total - b)
        if begin == end:
            return tuple(begin), None
        return None, (begin, end)
    if auto == "VALID":
        return (0,) * rank, None
    pads = ctx.attr("pads", [0] * (2 * rank))
    begin, end = pads[:rank], pads[rank:]
    if list(begin) == list(end):
        return tuple(begin), None
    return None, (begin, end)


@onnx_op("Conv")
def _conv(ctx):
    shp = ctx.shape_of_input(0)
    rank = len(shp) - 2
    if rank != 2:
        raise UnsupportedOnnxOpError(f"Conv rank {rank}", ctx.name)
    strides = tuple(ctx.attr("strides", [1] * rank))
    dil = tuple(ctx.attr("dilations", [1] * rank))
    groups = ctx.attr("group", 1)
    kernel = tuple(ctx.attr("kernel_shape")
                   or ctx.shape_of_input(1)[2:])
    pad_sym, pad_explicit = _conv_pads(ctx, rank, kernel, strides, dil)
    x = ctx.var(0)
    if pad_explicit is not None:
        begin, end = pad_explicit
        paddings = ((0, 0), (0, 0)) + tuple(
            (int(b), int(e)) for b, e in zip(begin, end))
        x = ctx.sd._add_op("pad", [x], paddings=paddings)
        pad_sym = (0,) * rank
    b = ctx.var_or_none(2)
    args = [x, ctx.var(1)] + ([b] if b is not None else [])
    return ctx.emit("conv2d", args, strides=strides, padding=pad_sym,
                    dilation=dil, data_format="NCHW", groups=int(groups))


def _pool_mapper(kind):
    def m(ctx: _Ctx):
        k = tuple(ctx.attr("kernel_shape"))
        if len(k) != 2:
            raise UnsupportedOnnxOpError(f"{kind} rank {len(k)}", ctx.name)
        s = tuple(ctx.attr("strides", [1] * len(k)))
        pad_sym, pad_explicit = _conv_pads(ctx, len(k), k, s)
        # Decide exclude-pad BEFORE the explicit-pad rewrite zeroes pad_sym:
        # ONNX default count_include_pad=0 divides by the number of
        # non-padding elements in each window.
        padded = (any(int(b) or int(e) for b, e in zip(*pad_explicit))
                  if pad_explicit is not None
                  else pad_sym == "SAME" or any(pad_sym))
        exclude_pad = (kind == "avgpool2d" and padded
                       and not ctx.attr("count_include_pad", 0))
        x = ctx.var(0)
        paddings = None
        if pad_explicit is not None:
            begin, end = pad_explicit
            paddings = ((0, 0), (0, 0)) + tuple(
                (int(b), int(e)) for b, e in zip(begin, end))
            fill = 0.0 if kind == "avgpool2d" else -np.inf
            x = ctx.sd._add_op("pad", [x], paddings=paddings,
                               constant_value=fill)
            pad_sym = (0,) * len(k)
        if not exclude_pad:
            return ctx.emit(kind, [x], kernel=k, strides=s, padding=pad_sym,
                            data_format="NCHW")
        # avgpool over zero-padded input divides by the full kernel area
        # (= count_include_pad=1 semantics; ops/nn.py _pool). Correct with a
        # precomputed (1, 1, oh, ow) scale k²/n_valid — pads, kernel, and
        # strides are all static, so no runtime mask pooling is needed.
        pooled = ctx.sd._add_op(kind, [x], kernel=k, strides=s,
                                padding=pad_sym, data_format="NCHW",
                                name=_safe(ctx.name) + "_incl")
        shp = ctx.shape_of_input(0)
        if pad_explicit is not None:
            begin, end = ([int(v) for v in pad_explicit[0]],
                          [int(v) for v in pad_explicit[1]])
        elif pad_sym == "SAME":   # SAME_UPPER: extra pad at the end
            begin, end = _same_pad_begin_end(shp[2:], k, s)
        else:
            begin = end = [int(v) for v in pad_sym]
        try:
            sdt = ctx.dtype_of_input(0)
        except Exception:
            sdt = np.dtype(np.float32)
        scale = _avgpool_exclude_pad_scale(shp[2:], k, s, begin, end,
                                           sdt)[None, None]
        c = ctx.sd.constant(_safe(ctx.name) + "_cip_scale", scale)
        return ctx.emit("multiply", [pooled, c])

    return m


def _same_pad_begin_end(hw, k, s):
    """SAME_UPPER padding split (extra pad at the end) per spatial dim —
    shared by the ONNX count_include_pad path and the TF AvgPool mapper."""
    begin, end = [], []
    for d, (kk, ss) in zip(hw, zip(k, s)):
        out = -(-int(d) // int(ss))
        total = max((out - 1) * int(ss) + int(kk) - int(d), 0)
        begin.append(total // 2)
        end.append(total - total // 2)
    return begin, end


def _avgpool_exclude_pad_scale(hw, k, s, begin, end, dtype):
    """(oh, ow) multiplier correcting a full-kernel-area average to the
    exclude-padding divisor (TF AvgPool / ONNX count_include_pad=0)."""
    counts = _pool_valid_counts(hw, k, s, begin, end)
    return ((k[0] * k[1]) / counts).astype(dtype)


def _pool_valid_counts(hw, k, s, begin, end):
    """Number of non-padding elements per pooling window, shape (oh, ow) —
    computed with an integral image over the validity mask."""
    H, W = int(hw[0]), int(hw[1])
    valid = np.zeros((H + begin[0] + end[0], W + begin[1] + end[1]),
                     np.float64)
    valid[begin[0]:begin[0] + H, begin[1]:begin[1] + W] = 1.0
    integ = np.zeros((valid.shape[0] + 1, valid.shape[1] + 1))
    integ[1:, 1:] = valid.cumsum(0).cumsum(1)
    oh = (valid.shape[0] - k[0]) // s[0] + 1
    ow = (valid.shape[1] - k[1]) // s[1] + 1
    i0 = np.arange(oh) * s[0]
    j0 = np.arange(ow) * s[1]
    counts = (integ[np.ix_(i0 + k[0], j0 + k[1])]
              - integ[np.ix_(i0, j0 + k[1])]
              - integ[np.ix_(i0 + k[0], j0)]
              + integ[np.ix_(i0, j0)])
    return np.maximum(counts, 1.0)


onnx_op("MaxPool")(_pool_mapper("maxpool2d"))
onnx_op("AveragePool")(_pool_mapper("avgpool2d"))


@onnx_op("GlobalAveragePool")
def _global_avg_pool(ctx):
    pooled = ctx.sd._add_op("global_avgpool", [ctx.var(0)],
                            data_format="NCHW")
    shp = ctx.shape_of_input(0)
    # ONNX keeps spatial dims as 1s
    return ctx.emit("reshape", [pooled],
                    shape=tuple(shp[:2]) + (1,) * (len(shp) - 2))


@onnx_op("GlobalMaxPool")
def _global_max_pool(ctx):
    shp = ctx.shape_of_input(0)
    red = ctx.sd._add_op("reduce_max", [ctx.var(0)],
                         dims=tuple(range(2, len(shp))), keep_dims=True)
    return ctx.emit("identity", [red])


@onnx_op("BatchNormalization")
def _batch_norm(ctx):
    if ctx.attr("training_mode", 0):
        raise UnsupportedOnnxOpError(
            "BatchNormalization(training_mode=1) — export for inference; "
            "training uses this framework's own BatchNormalization layer",
            ctx.name)
    x, gamma, beta, mean, var = (ctx.var(0), ctx.var(1), ctx.var(2),
                                 ctx.var(3), ctx.var(4))
    return ctx.emit("batchnorm", [x, mean, var, gamma, beta],
                    epsilon=ctx.attr("epsilon", 1e-5), axis=1)


@onnx_op("InstanceNormalization")
def _instance_norm(ctx):
    x = ctx.var(0)
    shp = ctx.shape_of_input(0)
    axes = tuple(range(2, len(shp)))
    eps = ctx.attr("epsilon", 1e-5)
    mean = ctx.sd._add_op("reduce_mean", [x], dims=axes, keep_dims=True)
    var = ctx.sd._add_op("reduce_variance", [x], dims=axes, keep_dims=True,
                         bias_corrected=False)
    xm = ctx.sd._add_op("subtract", [x, mean])
    denom = ctx.sd._add_op("sqrt", [ctx.sd._add_op("add", [var, float(eps)])])
    normed = ctx.sd._add_op("divide", [xm, denom])
    cshape = (1, int(shp[1])) + (1,) * (len(shp) - 2)
    g = ctx.sd._add_op("reshape", [ctx.var(1)], shape=cshape)
    b = ctx.sd._add_op("reshape", [ctx.var(2)], shape=cshape)
    return ctx.emit("add", [ctx.sd._add_op("multiply", [normed, g]), b])


@onnx_op("LayerNormalization")
def _layer_norm(ctx):
    axis = ctx.attr("axis", -1)
    eps = ctx.attr("epsilon", 1e-5)
    shp = ctx.shape_of_input(0)
    if axis % len(shp) != len(shp) - 1:
        raise UnsupportedOnnxOpError(
            f"LayerNormalization(axis={axis}, rank={len(shp)}) — only the "
            "last axis is supported", ctx.name)
    b = ctx.var_or_none(2)
    args = [ctx.var(0), ctx.var(1)] + ([b] if b is not None else [])
    return ctx.emit("layer_norm", args, axis=-1, epsilon=eps)


# --------------------------------------------------------------------------
# public API


class OnnxFrameworkImporter:
    """Reference-shaped entry (``OnnxFrameworkImporter.runImport``)."""

    @staticmethod
    def run_import(path_or_model,
                   input_shapes: Optional[Dict[str, Sequence[int]]] = None
                   ) -> SameDiff:
        model = _as_model(path_or_model)
        imp = _Importer(model, input_shapes)
        sd = imp.run()
        sd.onnx_placeholders = list(imp.placeholders)
        sd.onnx_outputs = list(imp.outputs)
        return sd

    runImport = run_import


def _as_model(src) -> "OIR.ModelProto":
    if isinstance(src, OIR.ModelProto):
        return src
    if isinstance(src, (bytes, bytearray)):
        m = OIR.ModelProto()
        m.ParseFromString(bytes(src))
        return m
    with open(src, "rb") as f:
        m = OIR.ModelProto()
        m.ParseFromString(f.read())
        return m


def import_onnx(path_or_model,
                input_shapes: Optional[Dict[str, Sequence[int]]] = None
                ) -> SameDiff:
    """ONNX ModelProto (.onnx path, bytes, or proto) → SameDiff graph
    executable/trainable on TPU (reference: ``SameDiff`` +
    ``OnnxFrameworkImporter``)."""
    return OnnxFrameworkImporter.run_import(path_or_model, input_shapes)
