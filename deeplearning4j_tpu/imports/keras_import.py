"""Keras import → MultiLayerNetwork / ComputationGraph.

Reference: dl4j-modelimport ``org.deeplearning4j.nn.modelimport.keras.
KerasModelImport`` / ``KerasSequentialModel`` + the ~60 ``KerasLayer``
mapping classes (SURVEY.md §2.3). Containers: legacy ``.h5`` (read with
h5py — the reference wraps HDF5 via JavaCPP ``Hdf5Archive``) AND the
Keras-3 native ``.keras`` zip (round 5; see ``_read_h5``).

Mapped layer types (round 5: 59 sequential + the functional importer's
merges — TimeDistributed, Masking (mask threaded to layers AND the
recurrent loss), Lambda via ``register_lambda``, ConvLSTM2D,
SeparableConv1D, ThresholdedReLU, GroupNormalization,
SpatialDropout1D/2D, 3D pad/crop/upsample, Dot/Minimum merges joined in
round 5; previously:
Dense, Conv1D/2D/3D, SeparableConv2D, DepthwiseConv2D, Conv2DTranspose,
Max/AveragePooling1D/2D/3D, GlobalMax/AveragePooling1D/2D/3D, Flatten,
Dropout, GaussianNoise/GaussianDropout/AlphaDropout, BatchNormalization,
LayerNormalization, Activation/ReLU/LeakyReLU/ELU/Softmax/PReLU,
ZeroPadding1D/2D, Cropping1D/2D, UpSampling1D/2D, Permute, Reshape,
RepeatVector, Embedding, LSTM, GRU (both reset_after forms), SimpleRNN,
Bidirectional(LSTM|GRU|SimpleRNN), InputLayer — plus functional-graph
Add/Subtract/Multiply/Average/Maximum/Concatenate and the
``register_custom_layer`` hook (reference KerasLayer.registerCustomLayer).

Layout conversions (the part the reference spends KerasLayer subclasses on):

- Keras is channels_last (NHWC); the network body here is NCHW. The imported
  model keeps Keras's INPUT contract (NHWC arrays in) via a transpose
  preprocessor at layer 0, weights are transposed once at import
  (HWIO→OIHW), and the first post-``Flatten`` Dense kernel's rows are
  permuted from HWC-flat to CHW-flat order so activations match exactly.
- Keras LSTM gates are ordered i,f,c,o in two matrices (kernel + recurrent);
  the fused layout here is one ``[nIn+nOut, 4*nOut]`` matrix in i,f,o,g
  order — stacked and column-permuted at import.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..nn.conf import layers as L
from ..nn.conf.builder import NeuralNetConfiguration
from ..nn.conf.inputs import CNNInput, InputType, Preprocessor
from ..nn.multilayer import MultiLayerNetwork

_ACTIVATIONS = {
    "linear": "identity", "relu": "relu", "relu6": "relu6",
    "softmax": "softmax", "sigmoid": "sigmoid", "tanh": "tanh",
    # Keras gelu defaults to approximate=False (erf form)
    "gelu": "gelu_exact", "elu": "elu", "selu": "selu", "softplus": "softplus",
    "softsign": "softsign", "swish": "swish", "silu": "swish",
    "leaky_relu": "leakyrelu", "hard_sigmoid": "hardsigmoid", "mish": "mish",
    "exponential": "exp",
}


class UnsupportedKerasLayerError(NotImplementedError):
    def __init__(self, class_name: str, detail: str = ""):
        super().__init__(
            f"Keras layer {class_name!r} is not mapped yet"
            + (f" ({detail})" if detail else ""))


def _act(name: Optional[str]) -> str:
    if name is None:
        return "identity"
    if name not in _ACTIVATIONS:
        raise UnsupportedKerasLayerError("activation", name)
    return _ACTIVATIONS[name]


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return int(v[0]), int(v[1])
    return int(v), int(v)


def _triple(v) -> Tuple[int, int, int]:
    if isinstance(v, (list, tuple)):
        return int(v[0]), int(v[1]), int(v[2])
    return int(v), int(v), int(v)


def _flatten_perm(shape) -> np.ndarray:
    """Kernel row permutation mapping Keras's channels-last Flatten order
    to this body's channels-first order; shape = (C, *spatial)."""
    c, spatial = int(shape[0]), tuple(int(s) for s in shape[1:])
    nd = len(spatial)
    arr = np.arange(int(np.prod(shape))).reshape(*spatial, c)
    return arr.transpose((nd,) + tuple(range(nd))).ravel()


def _permute_per_feature(tree: Dict[str, Any], perm: np.ndarray) -> None:
    """Apply the Flatten row permutation to per-feature parameter vectors
    (LayerNorm gain/bias, PReLU alpha, BN gamma/beta/mean/var) of layers
    sitting between a Flatten and the Dense that consumes the permute: the
    body's flattened activations are in CHW order while Keras stored these
    vectors over HWC-flattened features."""
    n = perm.shape[0]
    for k, v in tree.items():
        a = np.asarray(v)
        if a.ndim == 1 and a.shape[0] == n:
            tree[k] = a[perm]


def _pad2d_spec(v) -> Tuple[int, int, int, int]:
    """Keras 2D padding/cropping spec → (top, bottom, left, right)."""
    if isinstance(v, int):
        return v, v, v, v
    a, b = v
    if isinstance(a, int):
        return a, a, b, b
    return int(a[0]), int(a[1]), int(b[0]), int(b[1])


# Custom-layer hook (reference: KerasLayer.registerCustomLayer): maps a
# Keras class_name to a callable ``(config, weights) -> (Layer, setter)``
# where ``setter`` is ``None`` or ``setter(params_dict)`` filling imported
# weights (add ``setter.wants_state = True`` for ``setter(params, state)``).
# A custom layer that keeps the flattened row order intact (elementwise /
# normalization-style) may set ``layer.shape_preserving = True`` so it can
# sit between Flatten and Dense without tripping the permute-chain refusal.
_CUSTOM_LAYERS: Dict[str, Callable] = {}


def register_custom_layer(class_name: str, factory: Callable) -> None:
    _CUSTOM_LAYERS[class_name] = factory


def unregister_custom_layer(class_name: str) -> None:
    _CUSTOM_LAYERS.pop(class_name, None)


# Lambda-layer hook (reference: KerasLambdaLayer + SameDiffLambdaLayer —
# lambda BODIES are not portable across serialization, so the
# implementation is registered in code by the Lambda layer's NAME and
# looked up at import). ``fn`` maps a jnp array to a jnp array.
_LAMBDA_FNS: Dict[str, Callable] = {}


def register_lambda(name: str, fn: Callable) -> None:
    _LAMBDA_FNS[name] = fn


def unregister_lambda(name: str) -> None:
    _LAMBDA_FNS.pop(name, None)


def resolve_lambda(name: str) -> Callable:
    """Registered-lambda lookup shared by the Keras importer and the conf
    serde; raises with the registration recipe when absent."""
    fn = _LAMBDA_FNS.get(name)
    if fn is None:
        raise ValueError(
            f"Lambda {name!r}: lambda bodies are not portable/serializable "
            f"— register the implementation first with "
            f"deeplearning4j_tpu.imports.keras_import.register_lambda"
            f"({name!r}, fn)")
    return fn


class KerasModelImport:
    """Reference-shaped entry points."""

    # reference spelling: KerasLayer.registerCustomLayer
    register_custom_layer = staticmethod(register_custom_layer)
    registerCustomLayer = staticmethod(register_custom_layer)

    @staticmethod
    def import_keras_sequential_model_and_weights(h5_path: str) -> MultiLayerNetwork:
        return _import_sequential(h5_path)

    # reference spelling
    importKerasSequentialModelAndWeights = import_keras_sequential_model_and_weights

    @staticmethod
    def import_keras_model_and_weights(h5_path: str):
        """Functional/Model entry: Sequential topologies produce a
        MultiLayerNetwork, functional DAGs a ComputationGraph (reference:
        importKerasModelAndWeights returns either)."""
        f, cfg = _read_h5(h5_path)
        try:
            if cfg["class_name"] == "Sequential":
                return _import_sequential_parsed(f, cfg)
            from .keras_graph_import import import_functional_parsed

            return import_functional_parsed(f, cfg)
        finally:
            f.close()

    importKerasModelAndWeights = import_keras_model_and_weights


def _read_h5(h5_path: str):
    """Open a legacy ``.h5`` or a Keras-3 native ``.keras`` archive →
    (weights file, model config). The ``.keras`` zip holds config.json +
    model.weights.h5 (variables at ``layers/<name>/.../vars/<i>``); the
    returned h5py File is tagged ``_keras3_format`` so ``_layer_weights``
    reads the right layout."""
    import io
    import zipfile

    import h5py

    # HDF5 check FIRST: zipfile.is_zipfile scans trailing bytes for the
    # zip magic, so a legacy .h5 could be misclassified; and a zip that
    # is not a .keras archive must refuse actionably, not KeyError
    if not h5py.is_hdf5(h5_path) and zipfile.is_zipfile(h5_path):
        with zipfile.ZipFile(h5_path) as z:
            names = set(z.namelist())
            if "config.json" not in names or \
                    "model.weights.h5" not in names:
                raise ValueError(
                    f"{h5_path}: zip archive without config.json/"
                    "model.weights.h5 — not a Keras-3 .keras model file")
            cfg = json.loads(z.read("config.json"))
            f = h5py.File(io.BytesIO(z.read("model.weights.h5")), "r")
        f._keras3_format = True
        # the weights store DISCARDS layer names (user-chosen included)
        # and renumbers groups per model as snake_case(class) + per-class
        # counter in config layer order — map config name → group name
        f._keras3_names = _keras3_name_map(cfg)
        return f, cfg
    f = h5py.File(h5_path, "r")
    cfg = json.loads(f.attrs["model_config"])
    return f, cfg


def _keras3_snake(name: str) -> str:
    """Keras's to_snake_case (utils/naming.py): the auto-name base the
    weights store renumbers by."""
    import re

    name = re.sub(r"\W+", "", name)
    name = re.sub(r"(.)([A-Z][a-z]+)", r"\1_\2", name)
    return re.sub(r"([a-z])([A-Z])", r"\1_\2", name).lower()


def _keras3_name_map(cfg) -> Dict[str, str]:
    """config layer name → weights-store group name (per-class counter in
    config order; verified empirically: both auto and USER names are
    replaced by <snake_class>[_k] in the .keras variables file)."""
    mapping: Dict[str, str] = {}
    counters: Dict[str, int] = {}
    for kl in cfg.get("config", {}).get("layers", []):
        base = _keras3_snake(kl["class_name"])
        k = counters.get(base, 0)
        counters[base] = k + 1
        cname = kl.get("config", {}).get("name", kl["class_name"])
        mapping[cname] = base if k == 0 else f"{base}_{k}"
    return mapping


def _keras3_layer_weights(f, layer_name: str) -> List[np.ndarray]:
    """Keras-3 weights store: variables under ``layers/<name>`` at
    ``[nested group/]vars/<i>``. Order contract: a group's own ``vars``
    (numerically sorted) come first, then child groups — with
    ``forward_layer`` explicitly before ``backward_layer`` (alphabetical
    order would swap a Bidirectional's halves relative to the legacy
    ``weight_names`` order every mapper expects)."""
    import h5py

    layers_grp = f.get("layers")
    if layers_grp is None:
        return []
    group = getattr(f, "_keras3_names", {}).get(layer_name, layer_name)
    if group not in layers_grp:
        return []

    def child_key(k: str):
        return {"forward_layer": "0", "backward_layer": "1"}.get(k, k)

    def collect(g) -> List[np.ndarray]:
        out: List[np.ndarray] = []
        vars_grp = g.get("vars")
        if isinstance(vars_grp, h5py.Group):
            for k in sorted(vars_grp,
                            key=lambda s: (not s.isdigit(),
                                           int(s) if s.isdigit() else 0, s)):
                item = vars_grp[k]
                if isinstance(item, h5py.Dataset):
                    out.append(np.asarray(item))
        for k in sorted((kk for kk in g if kk != "vars"), key=child_key):
            item = g[k]
            if isinstance(item, h5py.Group):
                out.extend(collect(item))
        return out

    return collect(layers_grp[group])


def _layer_weights(f, layer_name: str) -> List[np.ndarray]:
    """Ordered weights via the layer group's weight_names attr (stable across
    Keras 2/3 nesting schemes). Weight-BEARING mappers must check for []
    and refuse — silently keeping random init would "import" a wrong model."""
    if getattr(f, "_keras3_format", False):
        return _keras3_layer_weights(f, layer_name)
    mw = f["model_weights"]
    if layer_name not in mw:
        return []
    grp = mw[layer_name]
    if "weight_names" not in grp.attrs:
        # fall back to collecting datasets in group order
        out: List[np.ndarray] = []

        def collect(g):
            import h5py

            for k in g:
                item = g[k]
                if isinstance(item, h5py.Dataset):
                    out.append(np.asarray(item))
                else:
                    collect(item)

        collect(grp)
        return out
    names = [n.decode() if isinstance(n, bytes) else str(n)
             for n in grp.attrs["weight_names"]]
    out = []
    for n in names:
        node = grp[n] if n in grp else f["model_weights"][n]
        out.append(np.asarray(node))
    return out


def _require_weights(ws: List[np.ndarray], cls: str, name: str) -> None:
    if not ws:
        raise ValueError(
            f"no weights found in h5 for layer {name!r} ({cls}); refusing to "
            "import with random initialization")


def _import_sequential(h5_path: str) -> MultiLayerNetwork:
    f, cfg = _read_h5(h5_path)
    try:
        return _import_sequential_parsed(f, cfg)
    finally:
        f.close()


def _import_sequential_parsed(f, cfg) -> MultiLayerNetwork:
    if cfg["class_name"] not in ("Sequential",):
        raise UnsupportedKerasLayerError(
            cfg["class_name"],
            "only Sequential topologies are mapped here; functional DAGs go "
            "through import_functional, arbitrary TF graphs through "
            "import_frozen_tf")
    builder = _SequentialBuilder()
    for kl in cfg["config"]["layers"]:
        builder.add(kl, f)
    return builder.finish()


class _SequentialBuilder:
    # layers that keep spatial layout (and therefore the flattened row
    # order) intact — the Flatten permute tracking passes through them
    _SHAPE_PRESERVING = ()   # filled after class body (needs L.*)

    def __init__(self):
        self.layers: List[L.Layer] = []
        self.weights: List[Optional[Callable]] = []  # per our-layer: params setter
        self.input_type: Optional[InputType] = None
        self.input_is_nhwc = False
        self.input_is_ndhwc = False
        self.flatten_pending = False      # saw Flatten; next Dense needs row permute
        # spatial shape at the Flatten: (C, H, W) or (C, D, H, W)
        self.flatten_shape: Optional[Tuple[int, ...]] = None
        self.cur_cnn: Optional[Tuple[int, ...]] = None  # (C,H,W)|(C,D,H,W)
        self.pending_activation: Optional[str] = None

    # -- input bookkeeping ------------------------------------------------
    def _set_input(self, batch_shape):
        dims = list(batch_shape[1:])
        if len(dims) == 3:  # NHWC
            h, w, c = dims
            self.input_type = InputType.convolutional(h, w, c)
            self.input_is_nhwc = True
            self.cur_cnn = (c, h, w)
        elif len(dims) == 4:  # NDHWC
            d, h, w, c = dims
            self.input_type = InputType.convolutional_3d(d, h, w, c)
            self.input_is_ndhwc = True
            self.cur_cnn = (c, d, h, w)
        elif len(dims) == 2:
            t, feat = dims
            self.input_type = InputType.recurrent(feat, t)
        elif len(dims) == 1:
            self.input_type = InputType.feed_forward(dims[0])
        else:
            raise UnsupportedKerasLayerError("InputLayer", f"rank {len(dims)}")

    def _update_cnn_shape(self, layer: L.Layer):
        """Track (C, H, W) / (C, D, H, W) through spatial layers for the
        Flatten permute."""
        if self.cur_cnn is None:
            return
        if isinstance(layer, self._SHAPE_PRESERVING):
            return
        if len(self.cur_cnn) == 3 and isinstance(
                layer, (L.ConvolutionLayer, L.SubsamplingLayer,
                        L.ZeroPaddingLayer, L.Cropping2D, L.Upsampling2D)):
            t = layer.set_input_type(CNNInput(*self.cur_cnn))
            self.cur_cnn = ((t.channels, t.height, t.width)
                            if isinstance(t, CNNInput) else None)
            return
        if len(self.cur_cnn) == 4 and isinstance(
                layer, (L.Convolution3DLayer, L.Subsampling3DLayer,
                        L.Upsampling3D, L.ZeroPadding3DLayer, L.Cropping3D,
                        L.ConvLSTM2DLayer)):
            from ..nn.conf.inputs import CNN3DInput

            c, d, h, w = self.cur_cnn
            t = layer.set_input_type(CNN3DInput(c, d, h, w))
            if isinstance(t, CNN3DInput):
                self.cur_cnn = (t.channels, t.depth, t.height, t.width)
            elif isinstance(t, CNNInput):   # ConvLSTM return_sequences=False
                self.cur_cnn = (t.channels, t.height, t.width)
            else:
                self.cur_cnn = None
            return
        self.cur_cnn = None  # left CNN space (Dense/GlobalPool/...)

    # -- per-layer mapping ------------------------------------------------
    def add(self, kl: Dict[str, Any], f) -> None:
        cls = kl["class_name"]
        c = kl.get("config", {})
        name = c.get("name", cls)
        ws = _layer_weights(f, name)

        if cls == "InputLayer":
            self._set_input(c.get("batch_shape") or c.get("batch_input_shape"))
            return
        if self.input_type is None and (c.get("batch_input_shape")
                                        or c.get("batch_shape")):
            # Keras-2-era h5: no InputLayer entry, the first real layer
            # carries batch_input_shape
            self._set_input(c.get("batch_input_shape") or c.get("batch_shape"))
        # registered custom layers; serialized names may carry the
        # register_keras_serializable package prefix ("pkg>ClassName")
        custom = _CUSTOM_LAYERS.get(cls) \
            or _CUSTOM_LAYERS.get(cls.split(">")[-1])
        if custom is not None:
            layer, setter = custom(c, ws)
            self._push(layer, setter)
            return
        if cls in ("Flatten",):
            # remember the spatial shape for the next Dense's row permute,
            # and materialize the flatten explicitly so ANY layer may
            # follow (LayerNormalization/PReLU/... — not just Dense).
            # Flatten of an already-flat tensor is an identity: keep an
            # already-pending permute instead of overwriting it with None
            if not (self.flatten_pending and self.flatten_shape is not None):
                self.flatten_pending = True
                self.flatten_shape = self.cur_cnn
            self.layers.append(L.FlattenLayer())
            self.weights.append(None)
            self.cur_cnn = None
            return
        if cls == "Dropout":
            self.layers.append(L.DropoutLayer(rate=float(c["rate"])))
            self.weights.append(None)
            return
        if cls in ("Activation", "ReLU", "LeakyReLU", "Softmax", "ELU"):
            act = {"ReLU": "relu", "Softmax": "softmax", "ELU": "elu"}.get(cls)
            if cls == "LeakyReLU":
                # Keras layer default slope is 0.3 (op default is 0.01)
                slope = float(c.get("negative_slope", c.get("alpha", 0.3)))
                self.layers.append(L.ActivationLayer(activation="leakyrelu",
                                                     alpha=slope))
            elif cls == "ELU":
                self.layers.append(L.ActivationLayer(
                    activation="elu", alpha=float(c.get("alpha", 1.0))))
            else:
                self.layers.append(L.ActivationLayer(
                    activation=act or _act(c.get("activation"))))
            self.weights.append(None)
            return

        handler = getattr(self, f"_map_{cls}", None)
        if handler is None:
            raise UnsupportedKerasLayerError(cls)
        handler(c, ws)

    def _push(self, layer: L.Layer, setter: Optional[Callable]):
        self._update_cnn_shape(layer)
        if self.flatten_pending and self.flatten_shape is not None:
            if isinstance(layer, self._SHAPE_PRESERVING) \
                    or getattr(layer, "shape_preserving", False):
                # a shape-preserving layer between Flatten and Dense: its
                # per-feature weights (if any) see CHW-ordered activations
                # and must be permuted like the Dense kernel rows
                if setter is not None:
                    perm = _flatten_perm(self.flatten_shape)
                    inner = setter
                    if getattr(inner, "wants_state", False):
                        def setter(params, state, _i=inner, _p=perm):
                            _i(params, state)
                            _permute_per_feature(params, _p)
                            _permute_per_feature(state, _p)

                        setter.wants_state = True
                    else:
                        def setter(params, _i=inner, _p=perm):
                            _i(params)
                            _permute_per_feature(params, _p)
            else:
                # the pending HWC→CHW row permute can't be tracked through
                # this layer; applying it later would be wrong, dropping it
                # silently wrong the other way — refuse
                raise UnsupportedKerasLayerError(
                    type(layer).__name__,
                    "layer between Flatten and Dense does not preserve the "
                    "flattened row order; the HWC->CHW kernel permute cannot "
                    "be applied soundly")
        # Keras's activation="leaky_relu" kwarg means
        # keras.activations.leaky_relu with negative_slope=0.2; body layers
        # apply activations without an alpha channel (op default 0.01), so
        # split the activation into an explicit ActivationLayer that carries
        # the slope. (The standalone LeakyReLU LAYER defaults to 0.3 and is
        # handled in its own branch.)
        if (getattr(layer, "activation", None) == "leakyrelu"
                and isinstance(layer, (L.DenseLayer, L.ConvolutionLayer))):
            layer.activation = "identity"
            self.layers.append(layer)
            self.weights.append(setter)
            self.layers.append(L.ActivationLayer(activation="leakyrelu",
                                                 alpha=0.2))
            self.weights.append(None)
            return
        self.layers.append(layer)
        self.weights.append(setter)

    def _map_Dense(self, c, ws):
        _require_weights(ws, 'Dense', c.get('name', '?'))
        units = int(c["units"])
        act = _act(c.get("activation"))
        use_bias = bool(c.get("use_bias", True))
        kernel = ws[0]
        bias = ws[1] if use_bias and len(ws) > 1 else None
        if self.flatten_pending and self.flatten_shape is not None:
            # keras flattens channels-last → rows in (spatial..., C) order;
            # the body here flattens channels-first. Permute rows once so
            # activations match (2D and 3D).
            kernel = kernel[_flatten_perm(self.flatten_shape)]
        self.flatten_pending = False

        if act == "softmax":
            layer = L.OutputLayer(n_out=units, activation="softmax",
                                  loss="mcxent", has_bias=use_bias)
        else:
            layer = L.DenseLayer(n_out=units, activation=act, has_bias=use_bias)

        def setter(params):
            params["W"] = np.asarray(kernel)
            if bias is not None:
                params["b"] = np.asarray(bias)

        self._push(layer, setter)

    def _map_Conv2D(self, c, ws):
        _require_weights(ws, 'Conv2D', c.get('name', '?'))
        if c.get("data_format", "channels_last") != "channels_last":
            raise UnsupportedKerasLayerError("Conv2D", "channels_first h5")
        layer = L.ConvolutionLayer(
            n_out=int(c["filters"]), kernel_size=_pair(c["kernel_size"]),
            stride=_pair(c.get("strides", 1)),
            dilation=_pair(c.get("dilation_rate", 1)),
            convolution_mode="same" if c.get("padding") == "same" else "truncate",
            activation=_act(c.get("activation")),
            has_bias=bool(c.get("use_bias", True)))
        kernel = ws[0].transpose(3, 2, 0, 1) if ws else None  # HWIO→OIHW
        bias = ws[1] if len(ws) > 1 else None

        def setter(params):
            params["W"] = kernel
            if bias is not None:
                params["b"] = bias

        self._push(layer, setter)

    def _map_DepthwiseConv2D(self, c, ws):
        _require_weights(ws, 'DepthwiseConv2D', c.get('name', '?'))
        layer = L.DepthwiseConvolution2D(
            n_out=0, kernel_size=_pair(c["kernel_size"]),
            stride=_pair(c.get("strides", 1)),
            depth_multiplier=int(c.get("depth_multiplier", 1)),
            convolution_mode="same" if c.get("padding") == "same" else "truncate",
            activation=_act(c.get("activation")),
            has_bias=bool(c.get("use_bias", True)))
        kernel = ws[0].transpose(3, 2, 0, 1) if ws else None  # [kh,kw,C,m]→[m,C,kh,kw]
        bias = ws[1] if len(ws) > 1 else None

        def setter(params):
            params["W"] = kernel
            if bias is not None:
                params["b"] = bias

        self._push(layer, setter)

    def _pool(self, c, kind):
        return L.SubsamplingLayer(
            pooling_type=kind, kernel_size=_pair(c.get("pool_size", 2)),
            stride=_pair(c.get("strides") or c.get("pool_size", 2)),
            convolution_mode="same" if c.get("padding") == "same" else "truncate")

    def _map_MaxPooling2D(self, c, ws):
        self._push(self._pool(c, "max"), None)

    def _map_AveragePooling2D(self, c, ws):
        self._push(self._pool(c, "avg"), None)

    def _map_GlobalAveragePooling2D(self, c, ws):
        self._push(L.GlobalPoolingLayer(pooling_type="avg"), None)

    def _map_GlobalMaxPooling2D(self, c, ws):
        self._push(L.GlobalPoolingLayer(pooling_type="max"), None)

    def _map_BatchNormalization(self, c, ws):
        _require_weights(ws, 'BatchNormalization', c.get('name', '?'))
        layer = L.BatchNormalization(decay=float(c.get("momentum", 0.99)),
                                     eps=float(c.get("epsilon", 1e-3)))
        # Keras stores only the enabled tensors, in order: [gamma?][beta?]
        # [moving_mean, moving_variance] — positional unpacking without the
        # scale/center flags would misassign them (all are shape [C], so
        # shape validation cannot catch it).
        scale = bool(c.get("scale", True))
        center = bool(c.get("center", True))
        expected = int(scale) + int(center) + 2
        if len(ws) != expected:
            raise UnsupportedKerasLayerError(
                "BatchNormalization",
                f"{c.get('name', '?')}: expected {expected} weight tensors "
                f"for scale={scale}, center={center}; got {len(ws)}")
        it = iter(ws)
        gamma = next(it) if scale else None
        beta = next(it) if center else None
        mean, var = next(it), next(it)

        def setter(params, state):
            if gamma is not None:
                params["gamma"] = gamma
            if beta is not None:
                params["beta"] = beta
            state["mean"] = mean
            state["var"] = var

        setter.wants_state = True
        self._push(layer, setter)

    def _map_Embedding(self, c, ws):
        _require_weights(ws, 'Embedding', c.get('name', '?'))
        layer = L.EmbeddingSequenceLayer(n_out=int(c["output_dim"]))
        # our layer reads vocab from input_type.size; keras models declare the
        # sequence input as [T] ints and carry input_dim in the layer config —
        # rewrite the network input type to recurrent(vocab, timesteps=T)
        from ..nn.conf.inputs import FFInput, RNNInput

        if isinstance(self.input_type, FFInput) and not self.layers:
            self.input_type = InputType.recurrent(int(c["input_dim"]),
                                                  self.input_type.size)
        elif isinstance(self.input_type, RNNInput) and not self.layers:
            self.input_type = InputType.recurrent(int(c["input_dim"]),
                                                  self.input_type.timesteps)
        table = ws[0]

        def setter(params):
            params["W"] = table

        self._push(layer, setter)

    def _map_LSTM(self, c, ws):
        _require_weights(ws, 'LSTM', c.get('name', '?'))
        if not c.get("return_sequences", False):
            raise UnsupportedKerasLayerError(
                "LSTM", "return_sequences=False (add GlobalPooling or use "
                "return_sequences=True)")
        layer, params = _convert_lstm(c, ws)
        self._push(layer, _dict_setter(params))

    def _map_GRU(self, c, ws):
        _require_weights(ws, 'GRU', c.get('name', '?'))
        if not c.get("return_sequences", False):
            raise UnsupportedKerasLayerError("GRU",
                                             "return_sequences=False")
        layer, params = _convert_gru(c, ws)
        self._push(layer, _dict_setter(params))

    def _map_SimpleRNN(self, c, ws):
        _require_weights(ws, 'SimpleRNN', c.get('name', '?'))
        if not c.get("return_sequences", False):
            raise UnsupportedKerasLayerError("SimpleRNN",
                                             "return_sequences=False")
        layer, params = _convert_simple_rnn(c, ws)
        self._push(layer, _dict_setter(params))

    def _map_Bidirectional(self, c, ws):
        name = c.get("name", "?")
        _require_weights(ws, 'Bidirectional', name)
        inner = c.get("layer", {})
        bwd_cfg = c.get("backward_layer")
        if bwd_cfg:
            # Keras always serializes backward_layer (auto-derived from the
            # forward layer); reject only a MATERIALLY different one — the
            # import runs both directions with the wrapped layer's config
            watch = ("units", "activation", "recurrent_activation",
                     "use_bias", "reset_after", "return_sequences",
                     "unit_forget_bias")
            ic0 = inner.get("config", {})
            bc0 = bwd_cfg.get("config", {})
            if (bwd_cfg.get("class_name") != inner.get("class_name")
                    or any(bc0.get(k, ic0.get(k)) != ic0.get(k)
                           for k in watch)):
                raise UnsupportedKerasLayerError(
                    "Bidirectional",
                    f"{name}: backward_layer differs from the wrapped "
                    "layer's config")
        inner_cls = inner.get("class_name")
        conv = {"LSTM": _convert_lstm, "GRU": _convert_gru,
                "SimpleRNN": _convert_simple_rnn}.get(inner_cls)
        if conv is None:
            raise UnsupportedKerasLayerError(
                "Bidirectional", f"{name}: wrapped {inner_cls!r}")
        ic = inner.get("config", {})
        if not ic.get("return_sequences", False):
            raise UnsupportedKerasLayerError(
                "Bidirectional", f"{name}: return_sequences=False")
        n_half = len(ws) // 2
        fwd_layer, fwd_params = conv(ic, ws[:n_half])
        _, bwd_params = conv(ic, ws[n_half:])
        mode = {"concat": "concat", "sum": "add", "mul": "mul",
                "ave": "average", "average": "average"}.get(
                    c.get("merge_mode", "concat"))
        if mode is None:
            raise UnsupportedKerasLayerError(
                "Bidirectional", f"merge_mode={c.get('merge_mode')!r}")
        layer = L.Bidirectional(layer=fwd_layer, mode=mode)

        def setter(params):
            # update (not replace) so initialized keys absent from the h5
            # keep their init values — except biases, which the converters
            # explicitly zero when use_bias=False
            params["fwd"].update(
                {k: np.asarray(v) for k, v in fwd_params.items()})
            params["bwd"].update(
                {k: np.asarray(v) for k, v in bwd_params.items()})

        self._push(layer, setter)

    # -- spatial extras ---------------------------------------------------
    def _map_SeparableConv2D(self, c, ws):
        _require_weights(ws, 'SeparableConv2D', c.get('name', '?'))
        if _pair(c.get("dilation_rate", 1)) != (1, 1):
            raise UnsupportedKerasLayerError("SeparableConv2D", "dilation")
        layer = L.SeparableConvolution2D(
            n_out=int(c["filters"]), kernel_size=_pair(c["kernel_size"]),
            stride=_pair(c.get("strides", 1)),
            depth_multiplier=int(c.get("depth_multiplier", 1)),
            convolution_mode="same" if c.get("padding") == "same" else "truncate",
            activation=_act(c.get("activation")),
            has_bias=bool(c.get("use_bias", True)))
        depth = ws[0].transpose(3, 2, 0, 1)   # [kh,kw,C,m] → [m,C,kh,kw]
        point = ws[1].transpose(3, 2, 0, 1)   # [1,1,C·m,F] → [F,C·m,1,1]
        bias = ws[2] if len(ws) > 2 else None

        def setter(params):
            params["dW"] = depth
            params["pW"] = point
            if bias is not None:
                params["b"] = bias

        self._push(layer, setter)

    def _map_Conv2DTranspose(self, c, ws):
        _require_weights(ws, 'Conv2DTranspose', c.get('name', '?'))
        if _pair(c.get("dilation_rate", 1)) != (1, 1):
            raise UnsupportedKerasLayerError("Conv2DTranspose", "dilation")
        layer = L.Deconvolution2D(
            n_out=int(c["filters"]), kernel_size=_pair(c["kernel_size"]),
            stride=_pair(c.get("strides", 1)),
            convolution_mode="same" if c.get("padding") == "same" else "truncate",
            activation=_act(c.get("activation")),
            has_bias=bool(c.get("use_bias", True)))
        kernel = ws[0].transpose(3, 2, 0, 1)  # [kh,kw,out,in] → [in,out,kh,kw]
        bias = ws[1] if len(ws) > 1 else None

        def setter(params):
            params["W"] = kernel
            if bias is not None:
                params["b"] = bias

        self._push(layer, setter)

    def _map_Conv1D(self, c, ws):
        _require_weights(ws, 'Conv1D', c.get('name', '?'))
        if c.get("padding") == "causal":
            raise UnsupportedKerasLayerError("Conv1D", "causal padding")
        layer = L.Convolution1DLayer(
            n_out=int(c["filters"]),
            kernel_size=int(_one(c["kernel_size"])),
            stride=int(_one(c.get("strides", 1))),
            dilation=int(_one(c.get("dilation_rate", 1))),
            convolution_mode="same" if c.get("padding") == "same" else "truncate",
            activation=_act(c.get("activation")),
            has_bias=bool(c.get("use_bias", True)))
        kernel = ws[0].transpose(2, 1, 0)     # [k,in,out] → [out,in,k]
        bias = ws[1] if len(ws) > 1 else None

        def setter(params):
            params["W"] = kernel
            if bias is not None:
                params["b"] = bias

        self._push(layer, setter)

    def _map_Conv3D(self, c, ws):
        _require_weights(ws, 'Conv3D', c.get('name', '?'))
        layer = L.Convolution3DLayer(
            n_out=int(c["filters"]), kernel_size=_triple(c["kernel_size"]),
            stride=_triple(c.get("strides", 1)),
            dilation=_triple(c.get("dilation_rate", 1)),
            convolution_mode="same" if c.get("padding") == "same" else "truncate",
            activation=_act(c.get("activation")),
            has_bias=bool(c.get("use_bias", True)))
        kernel = ws[0].transpose(4, 3, 0, 1, 2)  # [kd,kh,kw,in,out]→[out,in,kd,kh,kw]
        bias = ws[1] if len(ws) > 1 else None

        def setter(params):
            params["W"] = kernel
            if bias is not None:
                params["b"] = bias

        self._push(layer, setter)

    def _map_MaxPooling1D(self, c, ws):
        self._push(self._pool1d(c, "max"), None)

    def _map_AveragePooling1D(self, c, ws):
        self._push(self._pool1d(c, "avg"), None)

    def _pool1d(self, c, kind):
        if c.get("padding", "valid") == "same":
            raise UnsupportedKerasLayerError("Pooling1D", "same padding")
        return L.Subsampling1DLayer(
            pooling_type=kind, kernel_size=int(_one(c.get("pool_size", 2))),
            stride=int(_one(c.get("strides") or c.get("pool_size", 2))))

    def _map_MaxPooling3D(self, c, ws):
        self._push(self._pool3d(c, "max"), None)

    def _map_AveragePooling3D(self, c, ws):
        self._push(self._pool3d(c, "avg"), None)

    def _pool3d(self, c, kind):
        if c.get("padding", "valid") == "same":
            raise UnsupportedKerasLayerError("Pooling3D", "same padding")
        return L.Subsampling3DLayer(
            pooling_type=kind, kernel_size=_triple(c.get("pool_size", 2)),
            stride=_triple(c.get("strides") or c.get("pool_size", 2)))

    def _map_GlobalAveragePooling1D(self, c, ws):
        self._push(L.GlobalPoolingLayer(pooling_type="avg"), None)

    def _map_GlobalMaxPooling1D(self, c, ws):
        self._push(L.GlobalPoolingLayer(pooling_type="max"), None)

    def _map_GlobalAveragePooling3D(self, c, ws):
        self._push(L.GlobalPoolingLayer(pooling_type="avg"), None)

    def _map_GlobalMaxPooling3D(self, c, ws):
        self._push(L.GlobalPoolingLayer(pooling_type="max"), None)

    def _map_ZeroPadding2D(self, c, ws):
        self._push(L.ZeroPaddingLayer(
            padding=_pad2d_spec(c.get("padding", 1))), None)

    def _map_Cropping2D(self, c, ws):
        self._push(L.Cropping2D(
            cropping=_pad2d_spec(c.get("cropping", 0))), None)

    def _map_ZeroPadding1D(self, c, ws):
        v = c.get("padding", 1)
        lo, hi = (v, v) if isinstance(v, int) else (int(v[0]), int(v[1]))
        self._push(L.ZeroPadding1DLayer(padding=(lo, hi)), None)

    def _map_Cropping1D(self, c, ws):
        v = c.get("cropping", 0)
        lo, hi = (v, v) if isinstance(v, int) else (int(v[0]), int(v[1]))
        self._push(L.Cropping1D(cropping=(lo, hi)), None)

    def _map_UpSampling2D(self, c, ws):
        if c.get("interpolation", "nearest") != "nearest":
            raise UnsupportedKerasLayerError("UpSampling2D",
                                             c.get("interpolation"))
        self._push(L.Upsampling2D(size=_pair(c.get("size", 2))), None)

    def _map_UpSampling1D(self, c, ws):
        self._push(L.Upsampling1D(size=int(_one(c.get("size", 2)))), None)

    # -- normalization / activations / shape utils ------------------------
    def _map_LayerNormalization(self, c, ws):
        name = c.get("name", "?")
        _require_weights(ws, 'LayerNormalization', name)
        axis = c.get("axis", -1)
        axis = axis[0] if isinstance(axis, (list, tuple)) and len(axis) == 1 \
            else axis
        if axis != -1:
            # the rank isn't reliably known at map time, so a positive axis
            # can't be verified to be the feature axis — refuse rather than
            # import silently-wrong normalization
            raise UnsupportedKerasLayerError(
                "LayerNormalization",
                f"{name}: axis={c.get('axis')} (only the last axis, -1, "
                "is supported)")
        scale = bool(c.get("scale", True))
        center = bool(c.get("center", True))
        if len(ws) != int(scale) + int(center):
            raise UnsupportedKerasLayerError(
                "LayerNormalization",
                f"{name}: got {len(ws)} weights for scale={scale}, "
                f"center={center}")
        it = iter(ws)
        gamma = next(it) if scale else None
        beta = next(it) if center else None
        layer = L.LayerNormalization(eps=float(c.get("epsilon", 1e-3)))

        def setter(params):
            if gamma is not None:
                params["gain"] = gamma
            if beta is not None:
                params["bias"] = beta

        self._push(layer, setter)

    def _map_PReLU(self, c, ws):
        name = c.get("name", "?")
        _require_weights(ws, 'PReLU', name)
        alpha = np.asarray(ws[0])
        # our alpha is per-feature/per-channel; Keras's is per-element
        # unless shared_axes collapse the spatial dims
        squeezed = alpha.reshape(-1) if alpha.size == alpha.shape[-1] \
            else None
        if squeezed is None:
            raise UnsupportedKerasLayerError(
                "PReLU", f"{name}: per-element alpha of shape "
                f"{alpha.shape}; import supports per-channel/per-feature "
                "only (set shared_axes over the spatial dims)")
        layer = L.PReLULayer()

        def setter(params):
            params["alpha"] = squeezed

        self._push(layer, setter)

    def _map_RepeatVector(self, c, ws):
        self._push(L.RepeatVector(n=int(c["n"])), None)

    def _map_Permute(self, c, ws):
        self._push(L.Permute(dims=tuple(int(d) for d in c["dims"])), None)

    def _map_Reshape(self, c, ws):
        shape = tuple(int(d) for d in c["target_shape"])
        self._push(L.ReshapeLayer(shape=shape), None)

    # -- round-5 tail (VERDICT r4 missing #2) ------------------------------
    def _map_ThresholdedReLU(self, c, ws):
        self._push(L.ThresholdedReLULayer(theta=float(c.get("theta", 1.0))),
                   None)

    def _map_Masking(self, c, ws):
        self._push(L.MaskingLayer(mask_value=float(c.get("mask_value",
                                                         0.0))), None)

    def _map_Lambda(self, c, ws):
        name = c.get("name", "lambda")
        try:
            fn = resolve_lambda(name)
        except ValueError as e:
            raise UnsupportedKerasLayerError("Lambda", str(e)) from None
        self._push(L.LambdaLayer(fn=fn, name=name), None)

    def _map_TimeDistributed(self, c, ws):
        inner_cfg = c.get("layer", {})
        icls = inner_cfg.get("class_name")
        ic = inner_cfg.get("config", {})
        if icls == "Dense":
            _require_weights(ws, 'TimeDistributed(Dense)',
                             c.get('name', '?'))
            inner = L.DenseLayer(n_out=int(ic["units"]),
                                 activation=_act(ic.get("activation")),
                                 has_bias=bool(ic.get("use_bias", True)))
            kernel = ws[0]
            bias = ws[1] if len(ws) > 1 else None

            def setter(params):
                params["W"] = np.asarray(kernel)
                if bias is not None:
                    params["b"] = np.asarray(bias)
        elif icls == "Activation":
            inner = L.ActivationLayer(activation=_act(ic.get("activation")))
            setter = None
        elif icls == "Dropout":
            inner = L.DropoutLayer(rate=float(ic["rate"]))
            setter = None
        else:
            raise UnsupportedKerasLayerError(
                "TimeDistributed",
                f"inner layer {icls!r} (Dense/Activation/Dropout are "
                "mapped)")
        self._push(L.TimeDistributedLayer(inner=inner), setter)

    def _map_ConvLSTM2D(self, c, ws):
        name = c.get("name", "?")
        _require_weights(ws, 'ConvLSTM2D', name)
        if c.get("data_format", "channels_last") != "channels_last":
            raise UnsupportedKerasLayerError("ConvLSTM2D",
                                             "channels_first h5")
        if _pair(c.get("strides", 1)) != (1, 1) or \
                _pair(c.get("dilation_rate", 1)) != (1, 1):
            raise UnsupportedKerasLayerError(
                "ConvLSTM2D", f"{name}: strides/dilation != 1")
        if c.get("activation", "tanh") != "tanh":
            raise UnsupportedKerasLayerError(
                "ConvLSTM2D",
                f"{name}: activation={c.get('activation')!r} (tanh only)")
        if c.get("recurrent_activation", "sigmoid") != "sigmoid":
            raise UnsupportedKerasLayerError(
                "ConvLSTM2D", f"{name}: recurrent_activation="
                f"{c.get('recurrent_activation')!r} (sigmoid only)")
        layer = L.ConvLSTM2DLayer(
            n_out=int(c["filters"]), kernel_size=_pair(c["kernel_size"]),
            convolution_mode="same" if c.get("padding") == "same"
            else "truncate",
            return_sequences=bool(c.get("return_sequences", False)),
            has_bias=bool(c.get("use_bias", True)))
        # Keras: kernel [kh,kw,C,4F], recurrent [kh,kw,F,4F], bias [4F] —
        # the layer stores Keras gate order (i,f,c,o), so only HWIO→OIHW
        wx = ws[0].transpose(3, 2, 0, 1)
        wh = ws[1].transpose(3, 2, 0, 1)
        bias = ws[2] if len(ws) > 2 else None

        def setter(params):
            params["Wx"] = wx
            params["Wh"] = wh
            if bias is not None:
                params["b"] = bias

        self._push(layer, setter)

    def _map_SeparableConv1D(self, c, ws):
        name = c.get("name", "?")
        _require_weights(ws, 'SeparableConv1D', name)
        if int(_one(c.get("dilation_rate", 1))) != 1:
            raise UnsupportedKerasLayerError("SeparableConv1D",
                                             f"{name}: dilation")
        if c.get("padding") == "causal":
            raise UnsupportedKerasLayerError("SeparableConv1D",
                                             f"{name}: causal padding")
        layer = L.SeparableConvolution1D(
            n_out=int(c["filters"]),
            kernel_size=int(_one(c["kernel_size"])),
            stride=int(_one(c.get("strides", 1))),
            depth_multiplier=int(c.get("depth_multiplier", 1)),
            convolution_mode="same" if c.get("padding") == "same"
            else "truncate",
            activation=_act(c.get("activation")),
            has_bias=bool(c.get("use_bias", True)))
        depth = ws[0].transpose(2, 1, 0)[..., None]   # [k,C,m]→[m,C,k,1]
        point = ws[1].transpose(2, 1, 0)[..., None]   # [1,C·m,F]→[F,C·m,1,1]
        bias = ws[2] if len(ws) > 2 else None

        def setter(params):
            params["dW"] = depth
            params["pW"] = point
            if bias is not None:
                params["b"] = bias

        self._push(layer, setter)

    def _map_GroupNormalization(self, c, ws):
        name = c.get("name", "?")
        _require_weights(ws, 'GroupNormalization', name)
        axis = c.get("axis", -1)
        if axis != -1:
            raise UnsupportedKerasLayerError(
                "GroupNormalization", f"{name}: axis={axis} (channels-last "
                "h5 only)")
        if not bool(c.get("scale", True)) or not bool(c.get("center", True)):
            raise UnsupportedKerasLayerError(
                "GroupNormalization", f"{name}: scale/center disabled")
        groups = int(c.get("groups", 32))
        layer = L.GroupNormalizationLayer(
            groups=groups, eps=float(c.get("epsilon", 1e-3)))
        gamma, beta = ws[0], ws[1]

        def setter(params):
            params["gain"] = np.asarray(gamma)
            params["bias"] = np.asarray(beta)

        self._push(layer, setter)

    def _map_SpatialDropout1D(self, c, ws):
        self._push(L.SpatialDropoutLayer(rate=float(c["rate"])), None)

    def _map_SpatialDropout2D(self, c, ws):
        if c.get("data_format", "channels_last") not in (None,
                                                         "channels_last"):
            raise UnsupportedKerasLayerError("SpatialDropout2D",
                                             "channels_first h5")
        self._push(L.SpatialDropoutLayer(rate=float(c["rate"])), None)

    def _map_ZeroPadding3D(self, c, ws):
        p = c.get("padding", 1)
        spec = (p if isinstance(p, int)
                else tuple(tuple(e) if isinstance(e, (list, tuple)) else e
                           for e in p))
        self._push(L.ZeroPadding3DLayer(padding=spec), None)

    def _map_Cropping3D(self, c, ws):
        p = c.get("cropping", 1)
        spec = (p if isinstance(p, int)
                else tuple(tuple(e) if isinstance(e, (list, tuple)) else e
                           for e in p))
        self._push(L.Cropping3D(cropping=spec), None)

    def _map_UpSampling3D(self, c, ws):
        self._push(L.Upsampling3D(size=_triple(c.get("size", 2))), None)

    def _map_GaussianNoise(self, c, ws):
        self._push(L.GaussianNoiseLayer(stddev=float(c["stddev"])), None)

    def _map_GaussianDropout(self, c, ws):
        self._push(L.GaussianDropoutLayer(rate=float(c["rate"])), None)

    def _map_AlphaDropout(self, c, ws):
        self._push(L.AlphaDropoutLayer(rate=float(c["rate"])), None)

    # -- assembly ---------------------------------------------------------
    def finish(self) -> MultiLayerNetwork:
        return _finish_sequential(self)


_SequentialBuilder._SHAPE_PRESERVING = (
    L.BatchNormalization, L.DropoutLayer, L.ActivationLayer, L.PReLULayer,
    L.LayerNormalization, L.AlphaDropoutLayer, L.GaussianDropoutLayer,
    L.GaussianNoiseLayer, L.GroupNormalizationLayer, L.SpatialDropoutLayer,
    L.ThresholdedReLULayer)


def _one(v):
    return v[0] if isinstance(v, (list, tuple)) else v


def _dict_setter(vals: Dict[str, np.ndarray]) -> Callable:
    def setter(params):
        for k, v in vals.items():
            params[k] = np.asarray(v)

    return setter


def _convert_lstm(c, ws) -> Tuple[L.Layer, Dict[str, np.ndarray]]:
    units = int(c["units"])
    kernel, recurrent, bias = (list(ws) + [None] * 3)[:3]

    # keras gates i,f,c,o → fused i,f,o,g column order
    def remap_cols(m):
        i, fgate, g, o = np.split(m, 4, axis=-1)
        return np.concatenate([i, fgate, o, g], axis=-1)

    params = {"W": remap_cols(np.concatenate([kernel, recurrent], axis=0))}
    if bias is not None:
        params["b"] = remap_cols(np.asarray(bias)[None, :])[0]
    else:
        # use_bias=False: must overwrite the initialized forget-gate
        # bias of 1.0 — keeping it would silently diverge from Keras
        params["b"] = np.zeros((4 * units,), np.float32)
    return L.LSTM(n_out=units), params


def _convert_gru(c, ws) -> Tuple[L.Layer, Dict[str, np.ndarray]]:
    """Keras GRU gate order is z (update), r (reset), h (candidate); the
    GRU layer here wants [r, u] fused columns (reference gruCell order).
    reset_after=True (the Keras default) keeps separate input/recurrent
    candidate paths and a [2, 3n] bias."""
    units = n = int(c["units"])
    ra = bool(c.get("reset_after", True))
    kernel, recurrent = np.asarray(ws[0]), np.asarray(ws[1])
    bias = np.asarray(ws[2]) if len(ws) > 2 else None
    Wz, Wr, Wh = kernel[:, :n], kernel[:, n:2 * n], kernel[:, 2 * n:]
    Rz, Rr, Rh = (recurrent[:, :n], recurrent[:, n:2 * n],
                  recurrent[:, 2 * n:])
    w_ru = np.concatenate([np.concatenate([Wr, Wz], axis=1),
                           np.concatenate([Rr, Rz], axis=1)], axis=0)
    params: Dict[str, np.ndarray] = {"W_ru": w_ru}
    if ra:
        params["W_cx"] = Wh
        params["W_ch"] = Rh
        if bias is not None:
            bias = bias.reshape(2, 3 * n)
            bi, bh = bias[0], bias[1]
            params["b_ru"] = np.concatenate(
                [bi[n:2 * n] + bh[n:2 * n], bi[:n] + bh[:n]])
            params["b_cx"] = bi[2 * n:]
            params["b_ch"] = bh[2 * n:]
    else:
        params["W_c"] = np.concatenate([Wh, Rh], axis=0)
        if bias is not None:
            bias = bias.reshape(-1)
            params["b_ru"] = np.concatenate([bias[n:2 * n], bias[:n]])
            params["b_c"] = bias[2 * n:]
    return L.GRU(n_out=units, reset_after=ra), params


def _convert_simple_rnn(c, ws) -> Tuple[L.Layer, Dict[str, np.ndarray]]:
    layer = L.SimpleRnn(n_out=int(c["units"]),
                        activation=_act(c.get("activation", "tanh")))
    params = {"W": ws[0], "RW": ws[1]}
    if len(ws) > 2:
        params["b"] = ws[2]
    return layer, params


def _finish_sequential(self: "_SequentialBuilder") -> MultiLayerNetwork:
        if self.input_type is None:
            raise ValueError("model has no InputLayer / batch_shape")
        if not self.layers:
            raise ValueError("no layers imported")
        lb = NeuralNetConfiguration.builder().list()
        for layer in self.layers:
            lb.layer(layer)
        conf = lb.set_input_type(self.input_type).build()

        if self.input_is_nhwc or self.input_is_ndhwc:
            # keep Keras's channels-last input contract: transpose once on
            # entry, then run the channels-first body (weights were already
            # transposed at import)
            perm = (0, 3, 1, 2) if self.input_is_nhwc else (0, 4, 1, 2, 3)
            existing = conf.preprocessors.get(0)
            nhwc = Preprocessor("NhwcToNchw",
                                lambda x: x.transpose(*perm),
                                conf.layer_output_types[0]
                                if conf.layer_output_types else None)
            if existing is not None:
                conf.preprocessors[0] = Preprocessor(
                    f"NhwcToNchw+{existing.name}",
                    lambda x: existing(nhwc(x)), existing.out_type)
            else:
                conf.preprocessors[0] = nhwc

        model = MultiLayerNetwork(conf).init()
        for i, setter in enumerate(self.weights):
            if setter is None:
                continue
            params = _np_tree(model._params[i])
            if getattr(setter, "wants_state", False):
                state = {k: np.asarray(v) for k, v in model._states[i].items()}
                setter(params, state)
                for k, v in model._states[i].items():
                    expect = np.asarray(v).shape
                    got = np.asarray(state[k]).shape
                    if expect != got:
                        raise ValueError(
                            f"layer {i} state {k!r}: shape {got} != {expect}")
                model._states[i] = {k: np.asarray(v, dtype=np.float32)
                                    for k, v in state.items()}
            else:
                setter(params)
            _check_tree_shapes(model._params[i], params, f"layer {i}")
            model._params[i] = _jnp_tree(params)
        return model


def _np_tree(tree):
    """Params may nest (Bidirectional's fwd/bwd sub-dicts)."""
    return {k: (_np_tree(v) if isinstance(v, dict) else np.asarray(v))
            for k, v in tree.items()}


def _jnp_tree(tree):
    import jax.numpy as jnp

    return {k: (_jnp_tree(v) if isinstance(v, dict)
                else jnp.asarray(np.asarray(v, dtype=np.float32)))
            for k, v in tree.items()}


def _check_tree_shapes(expect_tree, got_tree, where: str) -> None:
    for k, v in expect_tree.items():
        got = got_tree[k]
        if isinstance(v, dict):
            _check_tree_shapes(v, got, f"{where}.{k}")
            continue
        expect = np.asarray(v).shape
        gshape = np.asarray(got).shape
        if expect != gshape:
            raise ValueError(
                f"{where} param {k!r}: imported shape {gshape} != "
                f"initialized shape {expect}")
