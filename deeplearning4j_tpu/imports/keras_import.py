"""Keras h5 import → MultiLayerNetwork.

Reference: dl4j-modelimport ``org.deeplearning4j.nn.modelimport.keras.
KerasModelImport`` / ``KerasSequentialModel`` + the ~60 ``KerasLayer``
mapping classes (SURVEY.md §2.3). This rebuild maps the common Sequential
surface; the h5 container is read with h5py (the reference wraps HDF5 via
JavaCPP ``Hdf5Archive``).

Layout conversions (the part the reference spends KerasLayer subclasses on):

- Keras is channels_last (NHWC); the network body here is NCHW. The imported
  model keeps Keras's INPUT contract (NHWC arrays in) via a transpose
  preprocessor at layer 0, weights are transposed once at import
  (HWIO→OIHW), and the first post-``Flatten`` Dense kernel's rows are
  permuted from HWC-flat to CHW-flat order so activations match exactly.
- Keras LSTM gates are ordered i,f,c,o in two matrices (kernel + recurrent);
  the fused layout here is one ``[nIn+nOut, 4*nOut]`` matrix in i,f,o,g
  order — stacked and column-permuted at import.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..nn.conf import layers as L
from ..nn.conf.builder import NeuralNetConfiguration
from ..nn.conf.inputs import CNNInput, InputType, Preprocessor
from ..nn.multilayer import MultiLayerNetwork

_ACTIVATIONS = {
    "linear": "identity", "relu": "relu", "relu6": "relu6",
    "softmax": "softmax", "sigmoid": "sigmoid", "tanh": "tanh",
    # Keras gelu defaults to approximate=False (erf form)
    "gelu": "gelu_exact", "elu": "elu", "selu": "selu", "softplus": "softplus",
    "softsign": "softsign", "swish": "swish", "silu": "swish",
    "leaky_relu": "leakyrelu", "hard_sigmoid": "hardsigmoid", "mish": "mish",
    "exponential": "exp",
}


class UnsupportedKerasLayerError(NotImplementedError):
    def __init__(self, class_name: str, detail: str = ""):
        super().__init__(
            f"Keras layer {class_name!r} is not mapped yet"
            + (f" ({detail})" if detail else ""))


def _act(name: Optional[str]) -> str:
    if name is None:
        return "identity"
    if name not in _ACTIVATIONS:
        raise UnsupportedKerasLayerError("activation", name)
    return _ACTIVATIONS[name]


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return int(v[0]), int(v[1])
    return int(v), int(v)


class KerasModelImport:
    """Reference-shaped entry points."""

    @staticmethod
    def import_keras_sequential_model_and_weights(h5_path: str) -> MultiLayerNetwork:
        return _import_sequential(h5_path)

    # reference spelling
    importKerasSequentialModelAndWeights = import_keras_sequential_model_and_weights

    @staticmethod
    def import_keras_model_and_weights(h5_path: str):
        """Functional/Model entry: Sequential topologies produce a
        MultiLayerNetwork, functional DAGs a ComputationGraph (reference:
        importKerasModelAndWeights returns either)."""
        f, cfg = _read_h5(h5_path)
        try:
            if cfg["class_name"] == "Sequential":
                return _import_sequential_parsed(f, cfg)
            from .keras_graph_import import import_functional_parsed

            return import_functional_parsed(f, cfg)
        finally:
            f.close()

    importKerasModelAndWeights = import_keras_model_and_weights


def _read_h5(h5_path: str):
    import h5py

    f = h5py.File(h5_path, "r")
    cfg = json.loads(f.attrs["model_config"])
    return f, cfg


def _layer_weights(f, layer_name: str) -> List[np.ndarray]:
    """Ordered weights via the layer group's weight_names attr (stable across
    Keras 2/3 nesting schemes). Weight-BEARING mappers must check for []
    and refuse — silently keeping random init would "import" a wrong model."""
    mw = f["model_weights"]
    if layer_name not in mw:
        return []
    grp = mw[layer_name]
    if "weight_names" not in grp.attrs:
        # fall back to collecting datasets in group order
        out: List[np.ndarray] = []

        def collect(g):
            import h5py

            for k in g:
                item = g[k]
                if isinstance(item, h5py.Dataset):
                    out.append(np.asarray(item))
                else:
                    collect(item)

        collect(grp)
        return out
    names = [n.decode() if isinstance(n, bytes) else str(n)
             for n in grp.attrs["weight_names"]]
    out = []
    for n in names:
        node = grp[n] if n in grp else f["model_weights"][n]
        out.append(np.asarray(node))
    return out


def _require_weights(ws: List[np.ndarray], cls: str, name: str) -> None:
    if not ws:
        raise ValueError(
            f"no weights found in h5 for layer {name!r} ({cls}); refusing to "
            "import with random initialization")


def _import_sequential(h5_path: str) -> MultiLayerNetwork:
    f, cfg = _read_h5(h5_path)
    try:
        return _import_sequential_parsed(f, cfg)
    finally:
        f.close()


def _import_sequential_parsed(f, cfg) -> MultiLayerNetwork:
    if cfg["class_name"] not in ("Sequential",):
        raise UnsupportedKerasLayerError(
            cfg["class_name"],
            "only Sequential topologies are mapped here; functional DAGs go "
            "through import_functional, arbitrary TF graphs through "
            "import_frozen_tf")
    builder = _SequentialBuilder()
    for kl in cfg["config"]["layers"]:
        builder.add(kl, f)
    return builder.finish()


class _SequentialBuilder:
    def __init__(self):
        self.layers: List[L.Layer] = []
        self.weights: List[Optional[Callable]] = []  # per our-layer: params setter
        self.input_type: Optional[InputType] = None
        self.input_is_nhwc = False
        self.flatten_pending = False      # saw Flatten; next Dense needs row permute
        self.flatten_shape: Optional[Tuple[int, int, int]] = None  # (C, H, W)
        self.cur_cnn: Optional[Tuple[int, int, int]] = None        # (C, H, W)
        self.pending_activation: Optional[str] = None

    # -- input bookkeeping ------------------------------------------------
    def _set_input(self, batch_shape):
        dims = list(batch_shape[1:])
        if len(dims) == 3:  # NHWC
            h, w, c = dims
            self.input_type = InputType.convolutional(h, w, c)
            self.input_is_nhwc = True
            self.cur_cnn = (c, h, w)
        elif len(dims) == 2:
            t, feat = dims
            self.input_type = InputType.recurrent(feat, t)
        elif len(dims) == 1:
            self.input_type = InputType.feed_forward(dims[0])
        else:
            raise UnsupportedKerasLayerError("InputLayer", f"rank {len(dims)}")

    def _update_cnn_shape(self, layer: L.Layer):
        """Track (C, H, W) through conv/pool layers for the Flatten permute."""
        if self.cur_cnn is None:
            return
        if not isinstance(layer, (L.ConvolutionLayer, L.SubsamplingLayer,
                                  L.BatchNormalization, L.DropoutLayer,
                                  L.ActivationLayer)):
            self.cur_cnn = None  # left CNN space (Dense/GlobalPool/...)
            return
        if isinstance(layer, (L.BatchNormalization, L.DropoutLayer,
                              L.ActivationLayer)):
            return  # shape-preserving
        t = layer.set_input_type(CNNInput(*self.cur_cnn))
        if isinstance(t, CNNInput):
            self.cur_cnn = (t.channels, t.height, t.width)
        else:
            self.cur_cnn = None

    # -- per-layer mapping ------------------------------------------------
    def add(self, kl: Dict[str, Any], f) -> None:
        cls = kl["class_name"]
        c = kl.get("config", {})
        name = c.get("name", cls)
        ws = _layer_weights(f, name)

        if cls == "InputLayer":
            self._set_input(c.get("batch_shape") or c.get("batch_input_shape"))
            return
        if self.input_type is None and (c.get("batch_input_shape")
                                        or c.get("batch_shape")):
            # Keras-2-era h5: no InputLayer entry, the first real layer
            # carries batch_input_shape
            self._set_input(c.get("batch_input_shape") or c.get("batch_shape"))
        if cls in ("Flatten",):
            self.flatten_pending = True
            self.flatten_shape = self.cur_cnn
            return
        if cls == "Dropout":
            self.layers.append(L.DropoutLayer(rate=float(c["rate"])))
            self.weights.append(None)
            return
        if cls in ("Activation", "ReLU", "LeakyReLU", "Softmax", "ELU"):
            act = {"ReLU": "relu", "Softmax": "softmax", "ELU": "elu"}.get(cls)
            if cls == "LeakyReLU":
                # Keras layer default slope is 0.3 (op default is 0.01)
                slope = float(c.get("negative_slope", c.get("alpha", 0.3)))
                self.layers.append(L.ActivationLayer(activation="leakyrelu",
                                                     alpha=slope))
            elif cls == "ELU":
                self.layers.append(L.ActivationLayer(
                    activation="elu", alpha=float(c.get("alpha", 1.0))))
            else:
                self.layers.append(L.ActivationLayer(
                    activation=act or _act(c.get("activation"))))
            self.weights.append(None)
            return

        handler = getattr(self, f"_map_{cls}", None)
        if handler is None:
            raise UnsupportedKerasLayerError(cls)
        handler(c, ws)

    def _push(self, layer: L.Layer, setter: Optional[Callable]):
        self._update_cnn_shape(layer)
        # Keras's activation="leaky_relu" kwarg means
        # keras.activations.leaky_relu with negative_slope=0.2; body layers
        # apply activations without an alpha channel (op default 0.01), so
        # split the activation into an explicit ActivationLayer that carries
        # the slope. (The standalone LeakyReLU LAYER defaults to 0.3 and is
        # handled in its own branch.)
        if (getattr(layer, "activation", None) == "leakyrelu"
                and isinstance(layer, (L.DenseLayer, L.ConvolutionLayer))):
            layer.activation = "identity"
            self.layers.append(layer)
            self.weights.append(setter)
            self.layers.append(L.ActivationLayer(activation="leakyrelu",
                                                 alpha=0.2))
            self.weights.append(None)
            return
        self.layers.append(layer)
        self.weights.append(setter)

    def _map_Dense(self, c, ws):
        _require_weights(ws, 'Dense', c.get('name', '?'))
        units = int(c["units"])
        act = _act(c.get("activation"))
        use_bias = bool(c.get("use_bias", True))
        kernel = ws[0]
        bias = ws[1] if use_bias and len(ws) > 1 else None
        if self.flatten_pending and self.flatten_shape is not None:
            C, H, W = self.flatten_shape
            # keras flattens NHWC → rows in HWC order; the body here flattens
            # NCHW → CHW order. Permute rows once so activations match.
            perm = np.arange(H * W * C).reshape(H, W, C).transpose(2, 0, 1).ravel()
            kernel = kernel[perm]
        self.flatten_pending = False

        if act == "softmax":
            layer = L.OutputLayer(n_out=units, activation="softmax",
                                  loss="mcxent", has_bias=use_bias)
        else:
            layer = L.DenseLayer(n_out=units, activation=act, has_bias=use_bias)

        def setter(params):
            params["W"] = np.asarray(kernel)
            if bias is not None:
                params["b"] = np.asarray(bias)

        self._push(layer, setter)

    def _map_Conv2D(self, c, ws):
        _require_weights(ws, 'Conv2D', c.get('name', '?'))
        if c.get("data_format", "channels_last") != "channels_last":
            raise UnsupportedKerasLayerError("Conv2D", "channels_first h5")
        layer = L.ConvolutionLayer(
            n_out=int(c["filters"]), kernel_size=_pair(c["kernel_size"]),
            stride=_pair(c.get("strides", 1)),
            dilation=_pair(c.get("dilation_rate", 1)),
            convolution_mode="same" if c.get("padding") == "same" else "truncate",
            activation=_act(c.get("activation")),
            has_bias=bool(c.get("use_bias", True)))
        kernel = ws[0].transpose(3, 2, 0, 1) if ws else None  # HWIO→OIHW
        bias = ws[1] if len(ws) > 1 else None

        def setter(params):
            params["W"] = kernel
            if bias is not None:
                params["b"] = bias

        self._push(layer, setter)

    def _map_DepthwiseConv2D(self, c, ws):
        _require_weights(ws, 'DepthwiseConv2D', c.get('name', '?'))
        layer = L.DepthwiseConvolution2D(
            n_out=0, kernel_size=_pair(c["kernel_size"]),
            stride=_pair(c.get("strides", 1)),
            depth_multiplier=int(c.get("depth_multiplier", 1)),
            convolution_mode="same" if c.get("padding") == "same" else "truncate",
            activation=_act(c.get("activation")),
            has_bias=bool(c.get("use_bias", True)))
        kernel = ws[0].transpose(3, 2, 0, 1) if ws else None  # [kh,kw,C,m]→[m,C,kh,kw]
        bias = ws[1] if len(ws) > 1 else None

        def setter(params):
            params["W"] = kernel
            if bias is not None:
                params["b"] = bias

        self._push(layer, setter)

    def _pool(self, c, kind):
        return L.SubsamplingLayer(
            pooling_type=kind, kernel_size=_pair(c.get("pool_size", 2)),
            stride=_pair(c.get("strides") or c.get("pool_size", 2)),
            convolution_mode="same" if c.get("padding") == "same" else "truncate")

    def _map_MaxPooling2D(self, c, ws):
        self._push(self._pool(c, "max"), None)

    def _map_AveragePooling2D(self, c, ws):
        self._push(self._pool(c, "avg"), None)

    def _map_GlobalAveragePooling2D(self, c, ws):
        self._push(L.GlobalPoolingLayer(pooling_type="avg"), None)

    def _map_GlobalMaxPooling2D(self, c, ws):
        self._push(L.GlobalPoolingLayer(pooling_type="max"), None)

    def _map_BatchNormalization(self, c, ws):
        _require_weights(ws, 'BatchNormalization', c.get('name', '?'))
        layer = L.BatchNormalization(decay=float(c.get("momentum", 0.99)),
                                     eps=float(c.get("epsilon", 1e-3)))
        # Keras stores only the enabled tensors, in order: [gamma?][beta?]
        # [moving_mean, moving_variance] — positional unpacking without the
        # scale/center flags would misassign them (all are shape [C], so
        # shape validation cannot catch it).
        scale = bool(c.get("scale", True))
        center = bool(c.get("center", True))
        expected = int(scale) + int(center) + 2
        if len(ws) != expected:
            raise UnsupportedKerasLayerError(
                "BatchNormalization",
                f"{c.get('name', '?')}: expected {expected} weight tensors "
                f"for scale={scale}, center={center}; got {len(ws)}")
        it = iter(ws)
        gamma = next(it) if scale else None
        beta = next(it) if center else None
        mean, var = next(it), next(it)

        def setter(params, state):
            if gamma is not None:
                params["gamma"] = gamma
            if beta is not None:
                params["beta"] = beta
            state["mean"] = mean
            state["var"] = var

        setter.wants_state = True
        self._push(layer, setter)

    def _map_Embedding(self, c, ws):
        _require_weights(ws, 'Embedding', c.get('name', '?'))
        layer = L.EmbeddingSequenceLayer(n_out=int(c["output_dim"]))
        # our layer reads vocab from input_type.size; keras models declare the
        # sequence input as [T] ints and carry input_dim in the layer config —
        # rewrite the network input type to recurrent(vocab, timesteps=T)
        from ..nn.conf.inputs import FFInput, RNNInput

        if isinstance(self.input_type, FFInput) and not self.layers:
            self.input_type = InputType.recurrent(int(c["input_dim"]),
                                                  self.input_type.size)
        elif isinstance(self.input_type, RNNInput) and not self.layers:
            self.input_type = InputType.recurrent(int(c["input_dim"]),
                                                  self.input_type.timesteps)
        table = ws[0]

        def setter(params):
            params["W"] = table

        self._push(layer, setter)

    def _map_LSTM(self, c, ws):
        _require_weights(ws, 'LSTM', c.get('name', '?'))
        if not c.get("return_sequences", False):
            raise UnsupportedKerasLayerError(
                "LSTM", "return_sequences=False (add GlobalPooling or use "
                "return_sequences=True)")
        units = int(c["units"])
        layer = L.LSTM(n_out=units)
        kernel, recurrent, bias = (ws + [None] * 3)[:3]

        # keras gates i,f,c,o → fused i,f,o,g column order
        def remap_cols(m):
            i, fgate, g, o = np.split(m, 4, axis=-1)
            return np.concatenate([i, fgate, o, g], axis=-1)

        w = remap_cols(np.concatenate([kernel, recurrent], axis=0))
        b = remap_cols(bias[None, :])[0] if bias is not None else None

        def setter(params):
            params["W"] = w
            if b is not None:
                params["b"] = b

        self._push(layer, setter)

    def _map_SimpleRNN(self, c, ws):
        _require_weights(ws, 'SimpleRNN', c.get('name', '?'))
        if not c.get("return_sequences", False):
            raise UnsupportedKerasLayerError("SimpleRNN",
                                             "return_sequences=False")
        layer = L.SimpleRnn(n_out=int(c["units"]),
                            activation=_act(c.get("activation", "tanh")))
        kernel, recurrent, bias = (ws + [None] * 3)[:3]

        def setter(params):
            params["W"] = kernel
            params["RW"] = recurrent
            if bias is not None:
                params["b"] = bias

        self._push(layer, setter)

    # -- assembly ---------------------------------------------------------
    def finish(self) -> MultiLayerNetwork:
        if self.input_type is None:
            raise ValueError("model has no InputLayer / batch_shape")
        if not self.layers:
            raise ValueError("no layers imported")
        lb = NeuralNetConfiguration.builder().list()
        for layer in self.layers:
            lb.layer(layer)
        conf = lb.set_input_type(self.input_type).build()

        if self.input_is_nhwc:
            # keep Keras's NHWC input contract: transpose once on entry, then
            # run the NCHW body (weights were already transposed to OIHW)
            existing = conf.preprocessors.get(0)
            nhwc = Preprocessor("NhwcToNchw",
                                lambda x: x.transpose(0, 3, 1, 2),
                                conf.layer_output_types[0]
                                if conf.layer_output_types else None)
            if existing is not None:
                conf.preprocessors[0] = Preprocessor(
                    f"NhwcToNchw+{existing.name}",
                    lambda x: existing(nhwc(x)), existing.out_type)
            else:
                conf.preprocessors[0] = nhwc

        model = MultiLayerNetwork(conf).init()
        for i, setter in enumerate(self.weights):
            if setter is None:
                continue
            params = {k: np.asarray(v) for k, v in model._params[i].items()}
            if getattr(setter, "wants_state", False):
                state = {k: np.asarray(v) for k, v in model._states[i].items()}
                setter(params, state)
                for k, v in model._states[i].items():
                    expect = np.asarray(v).shape
                    got = np.asarray(state[k]).shape
                    if expect != got:
                        raise ValueError(
                            f"layer {i} state {k!r}: shape {got} != {expect}")
                model._states[i] = {k: np.asarray(v, dtype=np.float32)
                                    for k, v in state.items()}
            else:
                setter(params)
            for k, v in model._params[i].items():
                expect = np.asarray(v).shape
                got = np.asarray(params[k]).shape
                if expect != got:
                    raise ValueError(
                        f"layer {i} param {k!r}: imported shape {got} != "
                        f"initialized shape {expect}")
            import jax.numpy as jnp

            model._params[i] = {k: jnp.asarray(np.asarray(v, dtype=np.float32))
                                for k, v in params.items()}
        return model
