"""Golden-fixture builders: construct reference TF graphs locally.

No egress is available, so north-star import fixtures (BERT-base) are built
with the locally installed TF at randomly initialized weights and frozen to
GraphDefs — the graph TOPOLOGY is exactly what the canonical BERT encoder
emits (embedding lookups + additive position/type embeddings, LayerNorm as
Mean/SquaredDifference/Rsqrt, multi-head attention as Reshape/Transpose/
BatchMatMul/Softmax with additive mask bias, erf-GELU FFN, pooler), which is
what import conformance is about; trained weight VALUES are irrelevant to the
importer. Reference flow: SURVEY.md §3.4 (TFGraphTestZooModels BERT case).

TF is an import-time dependency of this module only.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np


def build_bert_frozen_graph(batch: int = 4, seq: int = 128, hidden: int = 768,
                            layers: int = 12, heads: int = 12,
                            intermediate: int = 3072, vocab: int = 30522,
                            type_vocab: int = 2, max_pos: int = 512,
                            seed: int = 0):
    """BERT encoder (base config by default) → frozen GraphDef.

    Returns (graph_def, input_names, n_params). Inputs:
    input_ids, token_type_ids, input_mask — all [batch, seq] int32. Output:
    pooled [batch, hidden] (tanh pooler over [CLS], the fine-tune surface).
    """
    import tensorflow as tf
    from tensorflow.python.framework.convert_to_constants import \
        convert_variables_to_constants_v2

    rng = np.random.RandomState(seed)
    std = 0.02

    def W(*shape):
        return tf.constant(rng.normal(0.0, std, shape).astype(np.float32))

    def zeros(*shape):
        return tf.constant(np.zeros(shape, np.float32))

    def ones(*shape):
        return tf.constant(np.ones(shape, np.float32))

    word_emb = W(vocab, hidden)
    type_emb = W(type_vocab, hidden)
    pos_emb = W(max_pos, hidden)
    p: Dict[str, Tuple] = {}
    for i in range(layers):
        p[f"l{i}"] = dict(
            q=(W(hidden, hidden), zeros(hidden)),
            k=(W(hidden, hidden), zeros(hidden)),
            v=(W(hidden, hidden), zeros(hidden)),
            o=(W(hidden, hidden), zeros(hidden)),
            ln1=(ones(hidden), zeros(hidden)),
            up=(W(hidden, intermediate), zeros(intermediate)),
            down=(W(intermediate, hidden), zeros(hidden)),
            ln2=(ones(hidden), zeros(hidden)),
        )
    emb_ln = (ones(hidden), zeros(hidden))
    pool_w, pool_b = W(hidden, hidden), zeros(hidden)
    head_dim = hidden // heads

    def layer_norm(x, gamma, beta):
        mu = tf.reduce_mean(x, axis=-1, keepdims=True)
        var = tf.reduce_mean(tf.math.squared_difference(x, mu), axis=-1,
                             keepdims=True)
        return (x - mu) * tf.math.rsqrt(var + 1e-12) * gamma + beta

    def gelu(x):
        return 0.5 * x * (1.0 + tf.math.erf(x / tf.sqrt(2.0)))

    def dense(x, wb):
        w, b = wb
        return tf.matmul(x, w) + b

    def split_heads(x):
        x = tf.reshape(x, [batch, seq, heads, head_dim])
        return tf.transpose(x, [0, 2, 1, 3])

    @tf.function
    def bert(input_ids, token_type_ids, input_mask):
        x = (tf.gather(word_emb, input_ids)
             + tf.gather(type_emb, token_type_ids)
             + pos_emb[:seq])
        x = layer_norm(x, *emb_ln)
        # additive attention bias from the padding mask
        bias = (1.0 - tf.cast(tf.reshape(input_mask, [batch, 1, 1, seq]),
                              tf.float32)) * -10000.0
        for i in range(layers):
            lp = p[f"l{i}"]
            q = split_heads(dense(x, lp["q"]))
            k = split_heads(dense(x, lp["k"]))
            v = split_heads(dense(x, lp["v"]))
            scores = tf.matmul(q, k, transpose_b=True) / float(np.sqrt(head_dim))
            probs = tf.nn.softmax(scores + bias)
            ctxv = tf.matmul(probs, v)
            ctxv = tf.reshape(tf.transpose(ctxv, [0, 2, 1, 3]),
                              [batch, seq, hidden])
            x = layer_norm(x + dense(ctxv, lp["o"]), *lp["ln1"])
            h = gelu(dense(x, lp["up"]))
            x = layer_norm(x + dense(h, lp["down"]), *lp["ln2"])
        cls = x[:, 0]
        pooled = tf.tanh(tf.matmul(cls, pool_w) + pool_b)
        return pooled

    specs = [tf.TensorSpec([batch, seq], tf.int32, name=n)
             for n in ("input_ids", "token_type_ids", "input_mask")]
    cf = bert.get_concrete_function(*specs)
    frozen = convert_variables_to_constants_v2(cf)
    gd = frozen.graph.as_graph_def()
    in_names = [t.name.split(":")[0] for t in frozen.inputs]
    n_params = (vocab + type_vocab + max_pos) * hidden + layers * (
        4 * (hidden * hidden + hidden) + 2 * 2 * hidden
        + hidden * intermediate + intermediate + intermediate * hidden + hidden
    ) + 2 * hidden + hidden * hidden + hidden
    return gd, in_names, n_params


def make_bert_batch(batch: int, seq: int, vocab: int, num_classes: int,
                    seed: int = 0):
    """Synthetic fine-tune minibatch: ids/types/mask + one-hot labels."""
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, vocab, (batch, seq)).astype(np.int32)
    types = np.zeros((batch, seq), np.int32)
    mask = np.ones((batch, seq), np.int32)
    labels = np.eye(num_classes, dtype=np.float32)[
        rng.randint(0, num_classes, batch)]
    return ids, types, mask, labels
