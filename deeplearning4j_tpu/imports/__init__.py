"""Model import — TF frozen GraphDef → SameDiff; ONNX → SameDiff; Keras h5
→ layer API.

Reference: nd4j ``samediff-import-{api,tensorflow,onnx}`` + legacy
``org.nd4j.imports.graphmapper.tf.TFGraphMapper`` and dl4j
``org.deeplearning4j.nn.modelimport.keras.KerasModelImport``
(SURVEY.md §2.1, §2.3, §3.4).
"""

from .keras_import import (KerasModelImport, UnsupportedKerasLayerError,
                           register_custom_layer, register_lambda,
                           resolve_lambda, unregister_custom_layer,
                           unregister_lambda)
from .keras_graph_import import import_functional
from .onnx_import import (OnnxFrameworkImporter, UnsupportedOnnxOpError,
                          import_onnx, onnx_op, supported_onnx_ops)
from .tf_graph_mapper import (TFGraphMapper, UnsupportedTFOpError,
                              import_frozen_tf, supported_tf_ops, tf_op)

__all__ = [
    "TFGraphMapper", "UnsupportedTFOpError", "import_frozen_tf",
    "supported_tf_ops", "tf_op", "KerasModelImport",
    "UnsupportedKerasLayerError", "import_functional",
    "register_custom_layer", "unregister_custom_layer",
    "register_lambda", "unregister_lambda", "resolve_lambda",
    "OnnxFrameworkImporter", "UnsupportedOnnxOpError", "import_onnx",
    "onnx_op", "supported_onnx_ops",
]
