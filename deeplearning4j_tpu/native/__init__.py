"""Native host-runtime helpers (libdatavec_native, C++ via ctypes).

SURVEY §7.1.2's stance — "native where the reference is native" — applied to
the ONE place host CPU still sits on the training path in this architecture:
ETL loops feeding the device (the reference's equivalents live in libnd4j's
CPU helpers and DataVec's native image loaders). The device compute path is
XLA; these helpers accelerate corpus scanning / pair generation.

Build-on-first-use: compiled with g++ into the package dir, loaded with
ctypes (no pybind11 in this image). Every caller MUST tolerate
``available() == False`` and fall back to the numpy path — toolchain absence
degrades performance, never correctness.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "datavec_native.cpp")

_lib = None
_tried = False


# Sanitizer build flavor (SURVEY §5.2: ASAN/UBSAN flavors for native code,
# the analog of libnd4j's SD_SANITIZE CMake toggle). Set
# DL4J_TPU_NATIVE_SANITIZE=address|undefined BEFORE first use; the
# sanitized .so needs the matching runtime preloaded in the host process
# (LD_PRELOAD=$(g++ -print-file-name=libasan.so)) — see
# tests/test_native.py::TestSanitizerFlavor for the harness.
_SANITIZE = os.environ.get("DL4J_TPU_NATIVE_SANITIZE", "")


def _so_path() -> str:
    return os.path.join(
        _HERE, f"libdatavec_native{'_' + _SANITIZE if _SANITIZE else ''}.so")


def _build() -> bool:
    flags = ["-O3"]
    if _SANITIZE:
        flags = ["-O1", "-g", f"-fsanitize={_SANITIZE}",
                 "-fno-omit-frame-pointer"]
    try:
        subprocess.run(
            ["g++", *flags, "-shared", "-fPIC", "-std=c++17", _SRC,
             "-o", _so_path()],
            check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def _load():
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    so = _so_path()
    if not os.path.exists(so) or \
            os.path.getmtime(so) < os.path.getmtime(_SRC):
        if not _build():
            return None
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        return None
    lib.sg_pairs.restype = ctypes.c_int64
    lib.sg_pairs.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64]
    lib.tokenize_spans.restype = ctypes.c_int64
    lib.tokenize_spans.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def sg_pairs(ids: np.ndarray, offsets: np.ndarray, window: int,
             keep: Optional[np.ndarray], seed: int
             ) -> Tuple[np.ndarray, np.ndarray]:
    """Skip-gram (center, context) pairs for a corpus chunk — the word2vec
    host hot loop in one native call. ids int32 concatenated sentences;
    offsets int64 [n_sent+1]."""
    lib = _load()
    assert lib is not None, "native library unavailable; guard with available()"
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    cap = int(2 * window * max(ids.size, 1))
    centers = np.empty(cap, dtype=np.int32)
    contexts = np.empty(cap, dtype=np.int32)
    keep_ptr = None
    if keep is not None:
        keep = np.ascontiguousarray(keep, dtype=np.float64)
        keep_ptr = keep.ctypes.data_as(ctypes.c_void_p)
    n = lib.sg_pairs(
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(offsets) - 1, window, keep_ptr, seed,
        centers.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        contexts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), cap)
    return centers[:n], contexts[:n]


def tokenize(text: str):
    """Whitespace tokens of a (possibly huge) string in one native pass."""
    lib = _load()
    assert lib is not None, "native library unavailable; guard with available()"
    raw = text.encode("utf-8")
    cap = max(len(raw) // 2 + 1, 16)
    starts = np.empty(cap, dtype=np.int64)
    lens = np.empty(cap, dtype=np.int64)
    n = lib.tokenize_spans(
        raw, len(raw),
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), cap)
    return [raw[starts[i]:starts[i] + lens[i]].decode("utf-8")
            for i in range(n)]
