// libdatavec_native — host-side ETL hot loops in C++.
//
// TPU-native analog of the reference's native host runtime (libnd4j's CPU
// helpers; SURVEY.md §2.2, §7.1.2 "native where the reference is native"):
// the DEVICE compute path is XLA, but the host stages that feed it — corpus
// scanning and training-pair generation — are plain CPU loops where C++
// beats numpy by avoiding per-sentence array bookkeeping. Exposed extern "C"
// for ctypes (no pybind11 in this image).
//
// RNG: xoshiro-style splitmix64 stream — statistical, not bitwise, parity
// with the numpy path (the project's declared RNG stance, SURVEY §7.3.5).

#include <cstdint>
#include <cstring>

extern "C" {

static inline uint64_t splitmix64(uint64_t &state) {
    uint64_t z = (state += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
}

static inline double uniform01(uint64_t &state) {
    return (splitmix64(state) >> 11) * (1.0 / 9007199254740992.0);
}

// Skip-gram training pairs for a WHOLE corpus chunk in one call.
//
// ids:        concatenated word indices of all sentences
// offsets:    n_sent+1 sentence boundaries into ids
// window:     max window; per-position reduced window b ~ U[1, window]
// keep:       per-vocab-word keep probability (frequent-word subsampling),
//             may be null for no subsampling
// seed:       rng seed for this chunk
// centers/contexts: caller-allocated output, capacity cap pairs
// Returns number of pairs written (<= cap).
int64_t sg_pairs(const int32_t *ids, const int64_t *offsets,
                 int64_t n_sent, int32_t window, const double *keep,
                 uint64_t seed, int32_t *centers, int32_t *contexts,
                 int64_t cap) {
    uint64_t state = seed ? seed : 0x853C49E6748FEA9BULL;
    int64_t out = 0;
    // scratch for the subsampled sentence (bounded by longest sentence)
    static thread_local int32_t *buf = nullptr;
    static thread_local int64_t buf_cap = 0;
    for (int64_t s = 0; s < n_sent; ++s) {
        const int32_t *sent = ids + offsets[s];
        int64_t n = offsets[s + 1] - offsets[s];
        if (n > buf_cap) {
            delete[] buf;
            buf_cap = n * 2;
            buf = new int32_t[buf_cap];
        }
        int64_t m = 0;
        if (keep) {
            for (int64_t i = 0; i < n; ++i)
                if (uniform01(state) < keep[sent[i]]) buf[m++] = sent[i];
        } else {
            std::memcpy(buf, sent, n * sizeof(int32_t));
            m = n;
        }
        if (m < 2) continue;
        for (int64_t i = 0; i < m; ++i) {
            int32_t b = 1 + (int32_t)(splitmix64(state) % (uint64_t)window);
            int64_t lo = i - b < 0 ? 0 : i - b;
            int64_t hi = i + b >= m ? m - 1 : i + b;
            for (int64_t j = lo; j <= hi; ++j) {
                if (j == i) continue;
                if (out >= cap) return out;
                centers[out] = buf[i];
                contexts[out] = buf[j];
                ++out;
            }
        }
    }
    return out;
}

// Vocab counting over a raw whitespace-delimited UTF-8 buffer: emits
// (token_offset, token_len) spans so Python interns strings once instead of
// per-token splitting. Returns span count (<= cap).
int64_t tokenize_spans(const char *text, int64_t len,
                       int64_t *starts, int64_t *lens, int64_t cap) {
    int64_t out = 0;
    int64_t i = 0;
    while (i < len) {
        while (i < len && (text[i] == ' ' || text[i] == '\t' ||
                           text[i] == '\n' || text[i] == '\r')) ++i;
        int64_t start = i;
        while (i < len && !(text[i] == ' ' || text[i] == '\t' ||
                            text[i] == '\n' || text[i] == '\r')) ++i;
        if (i > start) {
            if (out >= cap) return out;
            starts[out] = start;
            lens[out] = i - start;
            ++out;
        }
    }
    return out;
}

}  // extern "C"
