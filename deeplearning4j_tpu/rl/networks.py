"""RL network factories on SameDiff graphs.

Reference: rl4j ``network.dqn.DQNFactoryStdDense`` /
``network.ac.ActorCriticFactorySeparateStdDense`` — stdlib MLP factories
behind the learning algorithms. Here each network is ONE SameDiff graph
(→ one jitted XLA module for the whole update step, losses included),
exposing the small ``output / fit / clone`` protocol the learners consume.

``DuelingQNetwork`` adds the dueling decomposition (Wang et al., the
rl4j-era standard): Q(s,a) = V(s) + A(s,a) − mean_a A(s,a), which plugs
into ``QLearningDiscreteDense`` unchanged.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..autodiff.samediff import SameDiff, TrainingConfig
from ..data.dataset import DataSet
from ..learning import Adam


def _mlp_trunk(sd: SameDiff, x, obs_dim: int, hidden: Sequence[int],
               rng: np.random.RandomState, prefix: str = "h"):
    h = x
    n_in = obs_dim
    for i, n_out in enumerate(hidden):
        w = sd.var(f"{prefix}{i}_w", init=(rng.randn(n_in, n_out)
                                           * np.sqrt(2.0 / n_in))
                   .astype(np.float32))
        b = sd.var(f"{prefix}{i}_b", shape=(n_out,), init="zeros")
        h = sd.math.relu((h @ w) + b)
        n_in = n_out
    return h, n_in


def _head(sd: SameDiff, h, n_in: int, n_out: int, name: str,
          rng: np.random.RandomState):
    w = sd.var(f"{name}_w", init=(rng.randn(n_in, n_out)
                                  * np.sqrt(1.0 / n_in)).astype(np.float32))
    b = sd.var(f"{name}_b", shape=(n_out,), init="zeros")
    return (h @ w) + b


class SameDiffQNetwork:
    """Q network with the learner protocol (output / fit / clone).

    ``dueling=True`` builds the V/A decomposition; the MSE-vs-setTarget
    training contract is identical either way."""

    def __init__(self, obs_dim: int, n_actions: int,
                 hidden: Sequence[int] = (64, 64), lr: float = 1e-3,
                 dueling: bool = False, seed: int = 0):
        self.obs_dim = obs_dim
        self.n_actions = n_actions
        self.hidden = tuple(hidden)
        self.lr = lr
        self.dueling = dueling
        self.seed = seed
        rng = np.random.RandomState(seed)
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, obs_dim))
        y = sd.placeholder("y", shape=(None, n_actions))
        h, n_in = _mlp_trunk(sd, x, obs_dim, hidden, rng)
        if dueling:
            v = _head(sd, h, n_in, 1, "value", rng)              # [B, 1]
            a = _head(sd, h, n_in, n_actions, "adv", rng)        # [B, A]
            a_mean = sd.math.reduce_mean(a, dims=(-1,), keep_dims=True)
            q = (v + (a - a_mean)).rename("q")
        else:
            q = _head(sd, h, n_in, n_actions, "q_head", rng).rename("q")
        sd.loss_ops.mean_sqerr_loss(q, y).rename("loss")
        sd.set_loss_variables("loss")
        sd.set_training_config(TrainingConfig(updater=Adam(lr),
                                              loss_name="loss"))
        self.sd = sd

    def output(self, x):
        return self.sd.output({"x": np.asarray(x, np.float32)}, ["q"])["q"]

    def fit(self, ds: DataSet, epochs: int = 1):
        return self.sd.fit(ds, epochs=epochs)

    def clone(self) -> "SameDiffQNetwork":
        new = SameDiffQNetwork(self.obs_dim, self.n_actions, self.hidden,
                               self.lr, self.dueling, self.seed)
        new.copy_params_from(self)
        return new

    def copy_params_from(self, other: "SameDiffQNetwork") -> None:
        for n, v in other.sd._vars.items():
            if v.vtype == "VARIABLE":
                self.sd._vars[n].value = np.asarray(v.value)


def DuelingQNetwork(obs_dim: int, n_actions: int,
                    hidden: Sequence[int] = (64, 64), lr: float = 1e-3,
                    seed: int = 0) -> SameDiffQNetwork:
    return SameDiffQNetwork(obs_dim, n_actions, hidden, lr, dueling=True,
                            seed=seed)


class ActorCriticNetwork:
    """Shared-trunk actor-critic (reference:
    ``ActorCriticFactoryCompGraphStdDense``): π logits + V(s) heads, one
    combined update — policy gradient weighted by advantage, value MSE,
    entropy bonus — compiled as a single XLA module."""

    def __init__(self, obs_dim: int, n_actions: int,
                 hidden: Sequence[int] = (64, 64), lr: float = 3e-3,
                 entropy_beta: float = 0.01, value_coeff: float = 0.5,
                 seed: int = 0):
        self.obs_dim = obs_dim
        self.n_actions = n_actions
        self.hidden = tuple(hidden)
        self.lr = lr
        self.entropy_beta = entropy_beta
        self.value_coeff = value_coeff
        self.seed = seed
        rng = np.random.RandomState(seed)
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, obs_dim))
        actions = sd.placeholder("actions", shape=(None, n_actions))
        returns = sd.placeholder("returns", shape=(None,))
        adv = sd.placeholder("advantage", shape=(None,))
        h, n_in = _mlp_trunk(sd, x, obs_dim, hidden, rng)
        logits = _head(sd, h, n_in, n_actions, "policy", rng) \
            .rename("logits")
        value = sd.math.squeeze(
            _head(sd, h, n_in, 1, "value", rng), axis=(-1,)).rename("value")
        logp = sd.math.log_softmax(logits, axis=-1)
        taken_logp = sd.math.reduce_sum(actions * logp, dims=(-1,))
        pg = sd.math.neg(sd.math.reduce_mean(taken_logp * adv))
        v_err = value - returns
        v_loss = sd.math.reduce_mean(v_err * v_err)
        entropy = sd.math.neg(sd.math.reduce_mean(
            sd.math.reduce_sum(sd.math.softmax(logits, axis=-1) * logp,
                               dims=(-1,))))
        loss = (pg + v_loss * float(value_coeff)
                - entropy * float(entropy_beta)).rename("loss")
        sd.set_loss_variables("loss")
        sd.set_training_config(TrainingConfig(updater=Adam(lr),
                                              loss_name="loss"))
        self.sd = sd

    # -- inference --------------------------------------------------------
    def policy_and_value(self, x):
        out = self.sd.output({"x": np.asarray(x, np.float32)},
                             ["logits", "value"])
        return out["logits"].to_numpy(), out["value"].to_numpy()

    def action_probs(self, obs: np.ndarray) -> np.ndarray:
        logits, _ = self.policy_and_value(obs[None].astype(np.float32))
        z = logits[0] - logits[0].max()
        e = np.exp(z)
        return e / e.sum()

    # -- update -----------------------------------------------------------
    def train_batch(self, obs, action_onehot, returns, advantage) -> float:
        hist = self.sd.fit({
            "x": np.asarray(obs, np.float32),
            "actions": np.asarray(action_onehot, np.float32),
            "returns": np.asarray(returns, np.float32),
            "advantage": np.asarray(advantage, np.float32),
        }, epochs=1)
        return hist.final_loss()

    def clone(self) -> "ActorCriticNetwork":
        new = ActorCriticNetwork(self.obs_dim, self.n_actions, self.hidden,
                                 self.lr, self.entropy_beta,
                                 self.value_coeff, self.seed)
        for n, v in self.sd._vars.items():
            if v.vtype == "VARIABLE":
                new.sd._vars[n].value = np.asarray(v.value)
        return new
