"""MDP SPI + built-in environments.

Reference: rl4j ``org.deeplearning4j.rl4j.mdp.MDP`` (reset/step/isDone +
action/observation spaces; gym bridge). No gym in this image, so the classic
control dynamics ship inline: CartPole (standard published physics) and a
deterministic 1-D gridworld for fast convergence tests.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


class DiscreteSpace:
    def __init__(self, n: int):
        self.n = n

    def random_action(self, rng) -> int:
        return int(rng.integers(0, self.n))


class ObservationSpace:
    def __init__(self, shape: Tuple[int, ...]):
        self.shape = shape


class MDP:
    """reset() -> obs; step(action) -> (obs, reward, done, info)."""

    action_space: DiscreteSpace
    observation_space: ObservationSpace

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int):
        raise NotImplementedError

    def is_done(self) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        pass


class CartPole(MDP):
    """Classic cart-pole balancing (the rl4j quick-start environment —
    standard equations of motion, episode ends at |x|>2.4 or |θ|>12°)."""

    def __init__(self, seed: int = 0, max_steps: int = 500):
        self.rng = np.random.default_rng(seed)
        self.max_steps = max_steps
        self.action_space = DiscreteSpace(2)
        self.observation_space = ObservationSpace((4,))
        self._state = None
        self._steps = 0
        self._done = True

    def reset(self) -> np.ndarray:
        self._state = self.rng.uniform(-0.05, 0.05, size=4)
        self._steps = 0
        self._done = False
        return self._state.astype(np.float32)

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self._state
        force = 10.0 if action == 1 else -10.0
        g, mc, mp, l, tau = 9.8, 1.0, 0.1, 0.5, 0.02
        total = mc + mp
        pml = mp * l
        cos_t, sin_t = np.cos(theta), np.sin(theta)
        temp = (force + pml * theta_dot ** 2 * sin_t) / total
        theta_acc = (g * sin_t - cos_t * temp) / \
            (l * (4.0 / 3.0 - mp * cos_t ** 2 / total))
        x_acc = temp - pml * theta_acc * cos_t / total
        x += tau * x_dot
        x_dot += tau * x_acc
        theta += tau * theta_dot
        theta_dot += tau * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot])
        self._steps += 1
        self._done = bool(abs(x) > 2.4 or abs(theta) > 12 * np.pi / 180
                          or self._steps >= self.max_steps)
        return self._state.astype(np.float32), 1.0, self._done, {}

    def is_done(self) -> bool:
        return self._done


class GridWorld(MDP):
    """Deterministic 1-D corridor: start left, goal right; reward 1 at the
    goal, small step penalty — converges in a few hundred DQN steps (the
    fast CI environment)."""

    def __init__(self, size: int = 8, max_steps: int = 50):
        self.size = size
        self.max_steps = max_steps
        self.action_space = DiscreteSpace(2)      # 0=left, 1=right
        self.observation_space = ObservationSpace((size,))
        self._pos = 0
        self._steps = 0
        self._done = True

    def _obs(self) -> np.ndarray:
        o = np.zeros(self.size, np.float32)
        o[self._pos] = 1.0
        return o

    def reset(self) -> np.ndarray:
        self._pos = 0
        self._steps = 0
        self._done = False
        return self._obs()

    def step(self, action: int):
        self._pos = max(0, min(self.size - 1,
                               self._pos + (1 if action == 1 else -1)))
        self._steps += 1
        at_goal = self._pos == self.size - 1
        self._done = bool(at_goal or self._steps >= self.max_steps)
        reward = 1.0 if at_goal else -0.01
        return self._obs(), reward, self._done, {}

    def is_done(self) -> bool:
        return self._done
