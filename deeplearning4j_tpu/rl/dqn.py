"""Deep Q-learning (reference: rl4j QLearningDiscreteDense).

Reference shape: ``QLearning.QLConfiguration`` (gamma, epsilon schedule,
replay size, batch, target-net update period, double-DQN flag),
``ExpReplay`` ring buffer, ``EpsGreedy`` policy over a ``DQN`` network,
``learning.train()`` episode loop.

TPU shape: the Q network is an ordinary ``MultiLayerNetwork`` with an MSE
head, so the TD step reuses THE one compiled fit module — the TD target is
written into the network's own Q output (non-taken actions keep their
current Q ⇒ zero gradient), the same trick the reference's
``QLearningDiscrete.setTarget`` uses. Environment stepping stays on host
(SURVEY §7.3.6: RL env stepping is the canonical host-loop workload)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from ..data.dataset import DataSet
from .mdp import MDP


@dataclass
class QLConfiguration:
    """Mirrors the reference QLearning.QLConfiguration fields."""

    seed: int = 123
    max_epoch_step: int = 200         # max steps per episode
    max_step: int = 10_000            # total training steps
    exp_rep_max_size: int = 10_000
    batch_size: int = 32
    target_dqn_update_freq: int = 100
    update_start: int = 100           # steps before learning starts
    reward_factor: float = 1.0
    gamma: float = 0.99
    error_clamp: float = 1.0          # TD error clip (0 = off)
    min_epsilon: float = 0.05
    epsilon_nb_step: int = 3000       # linear decay horizon
    double_dqn: bool = True


class ExpReplay:
    """Uniform ring-buffer replay (reference ExpReplay)."""

    def __init__(self, max_size: int, obs_dim: int, seed: int = 0):
        self.max_size = max_size
        self._obs = np.zeros((max_size, obs_dim), np.float32)
        self._next_obs = np.zeros((max_size, obs_dim), np.float32)
        self._action = np.zeros(max_size, np.int32)
        self._reward = np.zeros(max_size, np.float32)
        self._done = np.zeros(max_size, np.float32)
        self._n = 0
        self._i = 0
        self._rng = np.random.default_rng(seed)

    def store(self, obs, action, reward, next_obs, done) -> None:
        i = self._i
        self._obs[i] = obs
        self._action[i] = action
        self._reward[i] = reward
        self._next_obs[i] = next_obs
        self._done[i] = float(done)
        self._i = (i + 1) % self.max_size
        self._n = min(self._n + 1, self.max_size)

    def __len__(self) -> int:
        return self._n

    def sample(self, batch: int):
        idx = self._rng.integers(0, self._n, size=batch)
        return (self._obs[idx], self._action[idx], self._reward[idx],
                self._next_obs[idx], self._done[idx])


class EpsGreedy:
    """Linear-decay epsilon-greedy (reference policy.EpsGreedy)."""

    def __init__(self, conf: QLConfiguration, rng):
        self.conf = conf
        self.rng = rng

    def epsilon(self, step: int) -> float:
        frac = min(step / max(self.conf.epsilon_nb_step, 1), 1.0)
        return 1.0 + (self.conf.min_epsilon - 1.0) * frac

    def next_action(self, q_values: np.ndarray, step: int, n_actions: int
                    ) -> int:
        if self.rng.random() < self.epsilon(step):
            return int(self.rng.integers(0, n_actions))
        return int(np.argmax(q_values))


class DQNPolicy:
    """Greedy play policy over a trained Q network (reference DQNPolicy)."""

    def __init__(self, network):
        self.network = network

    def next_action(self, obs: np.ndarray) -> int:
        q = self.network.output(obs[None].astype(np.float32)).to_numpy()[0]
        return int(np.argmax(q))

    def play(self, mdp: MDP, max_steps: int = 1000) -> float:
        obs = mdp.reset()
        total = 0.0
        for _ in range(max_steps):
            obs, r, done, _ = mdp.step(self.next_action(obs))
            total += r
            if done:
                break
        return total


class QLearningDiscreteDense:
    """The rl4j entry point: dense-observation discrete-action Q-learning.

    ``network`` must be a MultiLayerNetwork whose output layer is an
    identity-activation MSE head with ``n_out == mdp.action_space.n``.
    """

    def __init__(self, mdp: MDP, network, config: QLConfiguration):
        self.mdp = mdp
        self.net = network
        self.conf = config
        self.rng = np.random.default_rng(config.seed)
        obs_dim = int(np.prod(mdp.observation_space.shape))
        self.replay = ExpReplay(config.exp_rep_max_size, obs_dim,
                                seed=config.seed)
        self.target = network.clone()
        self.policy_eps = EpsGreedy(config, self.rng)
        self.episode_rewards: List[float] = []
        self.step_count = 0

    # -- TD update ---------------------------------------------------------
    def _learn_batch(self) -> None:
        c = self.conf
        obs, action, reward, next_obs, done = \
            self.replay.sample(c.batch_size)
        q_cur = self.net.output(obs).to_numpy()
        q_next_t = self.target.output(next_obs).to_numpy()
        if c.double_dqn:
            # action selection by the ONLINE net, evaluation by the target
            q_next_on = self.net.output(next_obs).to_numpy()
            best = np.argmax(q_next_on, axis=1)
            next_val = q_next_t[np.arange(len(best)), best]
        else:
            next_val = q_next_t.max(axis=1)
        td_target = reward * c.reward_factor + c.gamma * next_val * (1 - done)
        if c.error_clamp > 0:
            cur = q_cur[np.arange(len(action)), action]
            td_target = cur + np.clip(td_target - cur, -c.error_clamp,
                                      c.error_clamp)
        y = q_cur.copy()
        y[np.arange(len(action)), action] = td_target
        # non-taken actions keep their current Q -> zero gradient (the
        # reference's setTarget construction)
        self.net.fit(DataSet(obs, y), epochs=1)

    def _sync_target(self) -> None:
        self.target = self.net.clone()

    # -- training loop -----------------------------------------------------
    def train(self) -> List[float]:
        c = self.conf
        n_actions = self.mdp.action_space.n
        while self.step_count < c.max_step:
            obs = self.mdp.reset()
            ep_reward = 0.0
            for _ in range(c.max_epoch_step):
                q = self.net.output(
                    obs[None].astype(np.float32)).to_numpy()[0]
                action = self.policy_eps.next_action(q, self.step_count,
                                                     n_actions)
                next_obs, reward, done, _ = self.mdp.step(action)
                self.replay.store(obs, action, reward, next_obs, done)
                obs = next_obs
                ep_reward += reward
                self.step_count += 1
                if self.step_count >= c.update_start and \
                        len(self.replay) >= c.batch_size:
                    self._learn_batch()
                if self.step_count % c.target_dqn_update_freq == 0:
                    self._sync_target()
                if done or self.step_count >= c.max_step:
                    break
            self.episode_rewards.append(ep_reward)
        return self.episode_rewards

    def get_policy(self) -> DQNPolicy:
        return DQNPolicy(self.net)
