"""Asynchronous RL family: A3C and async n-step Q-learning.

Reference: rl4j ``async`` package — ``A3CDiscreteDense``,
``AsyncNStepQLearningDiscreteDense``, ``AsyncGlobal``/``AsyncThread``
(SURVEY §2.3 RL4J row). Structure kept: N worker threads with their own
environment instances collect t_max-step fragments and apply updates to
ONE shared global network; workers re-read the shared parameters at each
fragment boundary.

TPU-shaped differences (documented): the reference applies Hogwild-ish
gradient updates under its AsyncGlobal lock; here the whole update is one
jitted SameDiff step, serialized by the same kind of lock — worker
parallelism buys overlapped ENVIRONMENT stepping (the host-bound part,
SURVEY §7.3.6), while the math stays in single compiled modules.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from .mdp import MDP
from .networks import ActorCriticNetwork, SameDiffQNetwork


@dataclass
class A3CConfiguration:
    """Mirrors rl4j A3CDiscrete.A3CConfiguration."""

    seed: int = 123
    max_epoch_step: int = 200
    max_step: int = 8_000           # total env steps across all workers
    num_threads: int = 2
    nstep: int = 8                  # t_max fragment length
    gamma: float = 0.99
    reward_factor: float = 1.0


class ACPolicy:
    """Stochastic policy over an actor-critic net (reference: ACPolicy —
    samples from π; ``greedy=True`` plays argmax)."""

    def __init__(self, network: ActorCriticNetwork,
                 rng: Optional[np.random.Generator] = None,
                 greedy: bool = False):
        self.network = network
        self.rng = rng or np.random.default_rng(0)
        self.greedy = greedy

    def next_action(self, obs: np.ndarray) -> int:
        probs = self.network.action_probs(np.asarray(obs, np.float32))
        if self.greedy:
            return int(np.argmax(probs))
        return int(self.rng.choice(probs.size, p=probs))

    def play(self, mdp: MDP, max_steps: int = 1000) -> float:
        obs = mdp.reset()
        total = 0.0
        for _ in range(max_steps):
            obs, r, done, _ = mdp.step(self.next_action(obs))
            total += r
            if done:
                break
        return total


class _AsyncBase:
    """Shared worker/step accounting for the async learners."""

    def __init__(self, conf, mdp_factory):
        self.conf = conf
        self.mdp_factory = mdp_factory
        self._lock = threading.Lock()
        self._step_lock = threading.Lock()
        self.step_count = 0
        self.episode_rewards: List[float] = []

    def _take_steps(self, n: int) -> bool:
        with self._step_lock:
            if self.step_count >= self.conf.max_step:
                return False
            self.step_count += n
            return True

    def _record_episode(self, r: float) -> None:
        with self._step_lock:
            self.episode_rewards.append(r)

    def train(self):
        errors: List[BaseException] = []

        def run(tid):
            try:
                self._worker(tid)
            except BaseException as e:   # surface on the caller, not a
                errors.append(e)         # silently-dead daemon thread

        threads = [threading.Thread(target=run, args=(t,), daemon=True)
                   for t in range(self.conf.num_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return self.episode_rewards


class A3CDiscreteDense(_AsyncBase):
    """rl4j A3CDiscreteDense: dense observations, discrete actions.

    ``mdp_factory()`` must return a fresh MDP per worker."""

    def __init__(self, mdp_factory, network: ActorCriticNetwork,
                 config: A3CConfiguration):
        super().__init__(config, mdp_factory)
        self.net = network

    def _worker(self, tid: int) -> None:
        c = self.conf
        rng = np.random.default_rng(c.seed + tid)
        mdp = self.mdp_factory()
        policy = ACPolicy(self.net, rng)
        nA = mdp.action_space.n
        obs = mdp.reset()
        ep_reward, ep_steps = 0.0, 0
        while True:
            frag_obs, frag_act, frag_rew = [], [], []
            done = False
            for _ in range(c.nstep):
                a = policy.next_action(obs)
                nxt, r, done, _ = mdp.step(a)
                frag_obs.append(obs)
                frag_act.append(a)
                frag_rew.append(r * c.reward_factor)
                obs = nxt
                ep_reward += r
                ep_steps += 1
                if done or ep_steps >= c.max_epoch_step:
                    break
            if not self._take_steps(len(frag_obs)):
                return
            # n-step returns, bootstrapped with V(s_T) when not terminal
            if done or ep_steps >= c.max_epoch_step:
                boot = 0.0
            else:
                _, v = self.net.policy_and_value(
                    np.asarray(obs, np.float32)[None])
                boot = float(v[0])
            R = boot
            returns = np.zeros(len(frag_rew), np.float32)
            for i in reversed(range(len(frag_rew))):
                R = frag_rew[i] + c.gamma * R
                returns[i] = R
            ob = np.asarray(frag_obs, np.float32)
            _, values = self.net.policy_and_value(ob)
            adv = returns - values
            onehot = np.eye(nA, dtype=np.float32)[np.asarray(frag_act)]
            with self._lock:
                self.net.train_batch(ob, onehot, returns, adv)
            if done or ep_steps >= c.max_epoch_step:
                self._record_episode(ep_reward)
                obs = mdp.reset()
                ep_reward, ep_steps = 0.0, 0

    def get_policy(self) -> ACPolicy:
        return ACPolicy(self.net, greedy=True)


@dataclass
class AsyncQLConfiguration:
    """Mirrors rl4j AsyncNStepQLearning's AsyncQLConfiguration."""

    seed: int = 123
    max_epoch_step: int = 200
    max_step: int = 8_000
    num_threads: int = 2
    nstep: int = 5
    target_dqn_update_freq: int = 100   # in UPDATES, not env steps
    gamma: float = 0.99
    reward_factor: float = 1.0
    min_epsilon: float = 0.1
    epsilon_nb_step: int = 3000


class AsyncNStepQLearningDiscreteDense(_AsyncBase):
    """rl4j AsyncNStepQLearningDiscreteDense: worker threads, n-step
    targets from a shared target net, epsilon-greedy exploration."""

    def __init__(self, mdp_factory, network: SameDiffQNetwork,
                 config: AsyncQLConfiguration):
        super().__init__(config, mdp_factory)
        self.net = network
        self.target = network.clone()
        self._updates = 0

    def _epsilon(self, tid: int) -> float:
        c = self.conf
        frac = min(self.step_count / max(c.epsilon_nb_step, 1), 1.0)
        return 1.0 + (c.min_epsilon - 1.0) * frac

    def _worker(self, tid: int) -> None:
        from ..data.dataset import DataSet

        c = self.conf
        rng = np.random.default_rng(c.seed + tid)
        mdp = self.mdp_factory()
        nA = mdp.action_space.n
        obs = mdp.reset()
        ep_reward, ep_steps = 0.0, 0
        while True:
            frag_obs, frag_act, frag_rew = [], [], []
            done = False
            for _ in range(c.nstep):
                if rng.random() < self._epsilon(tid):
                    a = int(rng.integers(0, nA))
                else:
                    q = self.net.output(
                        np.asarray(obs, np.float32)[None]).to_numpy()[0]
                    a = int(np.argmax(q))
                nxt, r, done, _ = mdp.step(a)
                frag_obs.append(obs)
                frag_act.append(a)
                frag_rew.append(r * c.reward_factor)
                obs = nxt
                ep_reward += r
                ep_steps += 1
                if done or ep_steps >= c.max_epoch_step:
                    break
            if not self._take_steps(len(frag_obs)):
                return
            if done or ep_steps >= c.max_epoch_step:
                boot = 0.0
            else:
                qn = self.target.output(
                    np.asarray(obs, np.float32)[None]).to_numpy()[0]
                boot = float(qn.max())
            R = boot
            returns = np.zeros(len(frag_rew), np.float32)
            for i in reversed(range(len(frag_rew))):
                R = frag_rew[i] + c.gamma * R
                returns[i] = R
            ob = np.asarray(frag_obs, np.float32)
            with self._lock:
                y = np.array(self.net.output(ob).to_numpy())  # writable copy
                y[np.arange(len(frag_act)), frag_act] = returns
                self.net.fit(DataSet(ob, y), epochs=1)
                self._updates += 1
                if self._updates % c.target_dqn_update_freq == 0:
                    # parameter copy, NOT clone(): a clone rebuilds the
                    # graph and re-traces while every worker waits on this
                    # lock
                    self.target.copy_params_from(self.net)
            if done or ep_steps >= c.max_epoch_step:
                self._record_episode(ep_reward)
                obs = mdp.reset()
                ep_reward, ep_steps = 0.0, 0

    def get_policy(self):
        from .dqn import DQNPolicy

        return DQNPolicy(self.net)
