"""Reinforcement learning (reference: rl4j, SURVEY §2.3 row 26).

- ``mdp``             MDP SPI + CartPole / GridWorld environments
- ``dqn``             QLearningDiscreteDense, ExpReplay, EpsGreedy, DQNPolicy
- ``networks``        SameDiffQNetwork (+dueling), ActorCriticNetwork
- ``async_learning``  A3CDiscreteDense, AsyncNStepQLearningDiscreteDense,
                      ACPolicy
- ``history``         HistoryProcessor (crop/rescale/skip/stack)
"""

from .async_learning import (A3CConfiguration, A3CDiscreteDense, ACPolicy,
                             AsyncNStepQLearningDiscreteDense,
                             AsyncQLConfiguration)
from .dqn import (DQNPolicy, EpsGreedy, ExpReplay, QLConfiguration,
                  QLearningDiscreteDense)
from .history import HistoryProcessor, HistoryProcessorConfiguration
from .mdp import MDP, CartPole, DiscreteSpace, GridWorld, ObservationSpace
from .networks import (ActorCriticNetwork, DuelingQNetwork, SameDiffQNetwork)

__all__ = ["A3CConfiguration", "A3CDiscreteDense", "ACPolicy",
           "ActorCriticNetwork", "AsyncNStepQLearningDiscreteDense",
           "AsyncQLConfiguration", "CartPole", "DQNPolicy", "DiscreteSpace",
           "DuelingQNetwork", "EpsGreedy", "ExpReplay", "GridWorld",
           "HistoryProcessor", "HistoryProcessorConfiguration", "MDP",
           "ObservationSpace", "QLConfiguration", "QLearningDiscreteDense",
           "SameDiffQNetwork"]
