"""Reinforcement learning (reference: rl4j, SURVEY §2.3 row 26).

- ``mdp``             MDP SPI + CartPole / GridWorld environments
- ``dqn``             QLearningDiscreteDense, ExpReplay, EpsGreedy, DQNPolicy
- ``networks``        SameDiffQNetwork (+dueling), ActorCriticNetwork
- ``async_learning``  A3CDiscreteDense, AsyncNStepQLearningDiscreteDense,
                      ACPolicy
- ``history``         HistoryProcessor (crop/rescale/skip/stack)
- ``population``      FleetDQNPopulation — M agents' Q-networks as ONE
                      vmapped fleet (parallel.fleet), per-member
                      telemetry/early-stop/NaN-cull
"""

from .async_learning import (A3CConfiguration, A3CDiscreteDense, ACPolicy,
                             AsyncNStepQLearningDiscreteDense,
                             AsyncQLConfiguration)
from .dqn import (DQNPolicy, EpsGreedy, ExpReplay, QLConfiguration,
                  QLearningDiscreteDense)
from .history import HistoryProcessor, HistoryProcessorConfiguration
from .mdp import MDP, CartPole, DiscreteSpace, GridWorld, ObservationSpace
from .networks import (ActorCriticNetwork, DuelingQNetwork, SameDiffQNetwork)
from .population import FleetDQNPopulation

__all__ = ["A3CConfiguration", "A3CDiscreteDense", "ACPolicy",
           "ActorCriticNetwork", "AsyncNStepQLearningDiscreteDense",
           "AsyncQLConfiguration", "CartPole", "DQNPolicy", "DiscreteSpace",
           "DuelingQNetwork", "EpsGreedy", "ExpReplay",
           "FleetDQNPopulation", "GridWorld",
           "HistoryProcessor", "HistoryProcessorConfiguration", "MDP",
           "ObservationSpace", "QLConfiguration", "QLearningDiscreteDense",
           "SameDiffQNetwork"]
