"""Reinforcement learning (reference: rl4j, SURVEY §2.3 row 26).

- ``mdp``  MDP SPI + CartPole / GridWorld environments
- ``dqn``  QLearningDiscreteDense, ExpReplay, EpsGreedy, DQNPolicy
"""

from .dqn import (DQNPolicy, EpsGreedy, ExpReplay, QLConfiguration,
                  QLearningDiscreteDense)
from .mdp import MDP, CartPole, DiscreteSpace, GridWorld, ObservationSpace

__all__ = ["CartPole", "DQNPolicy", "DiscreteSpace", "EpsGreedy",
           "ExpReplay", "GridWorld", "MDP", "ObservationSpace",
           "QLConfiguration", "QLearningDiscreteDense"]
