"""HistoryProcessor: observation preprocessing + frame stacking.

Reference: rl4j ``util.HistoryProcessor`` + ``IHistoryProcessor.Configuration``
(SURVEY §2.3 RL4J row) — the ALE pipeline: crop → rescale → per-frame skip
→ ring of the last ``history_length`` frames, stacked as the network input.
The reference leans on OpenCV for the image ops; here they are pure-numpy
(slicing crop, nearest-neighbor rescale), which covers the same contract
without a native dependency.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np


@dataclass
class HistoryProcessorConfiguration:
    """Mirrors IHistoryProcessor.Configuration."""

    history_length: int = 4
    rescaled_width: int = 0          # 0 = keep
    rescaled_height: int = 0
    crop_top: int = 0
    crop_bottom: int = 0
    crop_left: int = 0
    crop_right: int = 0
    skip_frame: int = 1              # record every k-th frame


class HistoryProcessor:
    def __init__(self, conf: Optional[HistoryProcessorConfiguration] = None):
        self.conf = conf or HistoryProcessorConfiguration()
        self._frames: deque = deque(maxlen=self.conf.history_length)
        self._calls = 0

    # -- per-frame transform ----------------------------------------------
    def preprocess(self, obs: np.ndarray) -> np.ndarray:
        c = self.conf
        out = np.asarray(obs, np.float32)
        if out.ndim >= 2 and (c.crop_top or c.crop_bottom or c.crop_left
                              or c.crop_right):
            h, w = out.shape[0], out.shape[1]
            out = out[c.crop_top:h - c.crop_bottom or h,
                      c.crop_left:w - c.crop_right or w]
        if out.ndim >= 2 and c.rescaled_width and c.rescaled_height:
            h, w = out.shape[0], out.shape[1]
            ri = (np.arange(c.rescaled_height) * h
                  // c.rescaled_height)
            ci = (np.arange(c.rescaled_width) * w // c.rescaled_width)
            out = out[ri][:, ci]
        return out

    # -- ring -------------------------------------------------------------
    def record(self, obs: np.ndarray) -> bool:
        """Offer a raw frame; returns True when it was added (respecting
        skip_frame)."""
        take = (self._calls % max(self.conf.skip_frame, 1)) == 0
        self._calls += 1
        if take:
            self.add(obs)
        return take

    def add(self, obs: np.ndarray) -> None:
        self._frames.append(self.preprocess(obs))

    def start_episode(self, obs: np.ndarray) -> None:
        """Reset the ring, filling all slots with the first frame (the
        reference pads the initial stack the same way)."""
        self._frames.clear()
        self._calls = 0
        f = self.preprocess(obs)
        for _ in range(self.conf.history_length):
            self._frames.append(f)

    def is_ready(self) -> bool:
        return len(self._frames) == self.conf.history_length

    def get_history(self) -> np.ndarray:
        """Stacked [history_length, *frame_shape] float32."""
        assert self.is_ready(), "history ring not yet full"
        return np.stack(list(self._frames)).astype(np.float32)

    def flat_history(self) -> np.ndarray:
        return self.get_history().reshape(-1)

    @property
    def shape(self) -> Tuple[int, ...]:
        assert self._frames, "no frames recorded"
        return (self.conf.history_length,) + tuple(self._frames[-1].shape)
