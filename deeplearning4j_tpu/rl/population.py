"""RL population training over a vmapped fleet (ROADMAP 5(a), rl hook).

The reference's rl4j trains one agent per process; its async family
(``async_learning``) multiplies HOST threads against one shared network.
This module multiplies the NETWORKS instead: M DQN agents — separate
environments, separate replay buffers, separate exploration streams —
whose Q-networks are ONE :class:`parallel.fleet.FleetTrainer` population.
Every TD update for all M agents is a single vmapped+jitted step, action
selection batches all M observations through one vmapped inference
dispatch, and the per-member telemetry bus drives early-stop/NaN-cull of
diverged members without touching the others (bit-isolation proven in
tests/test_fleet.py).

Env stepping and replay stay on host per agent (SURVEY §7.3.6: RL env
stepping is the canonical host-loop workload) — the device cost of the
population is one step dispatch regardless of M.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from ..data.dataset import DataSet  # noqa: F401  (re-export convenience)
from ..parallel.fleet import FleetTrainer
from .dqn import EpsGreedy, ExpReplay, QLConfiguration
from .mdp import MDP


class FleetDQNPopulation:
    """M independent DQN agents over one fleet-trained Q-network stack.

    ``mdp_factory(i)`` builds agent i's environment; ``base_net`` is the
    shared Q-network architecture (an init()-ed MultiLayerNetwork with an
    identity-activation MSE head, exactly as ``QLearningDiscreteDense``
    takes); ``grid`` optionally sweeps per-member hyperparameters (lr /
    l2 / dropout) so a population IS a hyperparameter search. Listeners
    (``NanSentinelListener("cull")``, :class:`FleetEarlyStop`, sinks)
    attach straight onto the underlying fleet.
    """

    def __init__(self, mdp_factory: Callable[[int], MDP], base_net,
                 config: QLConfiguration, n_members: int,
                 grid=None, listeners=()):
        self.conf = config
        if grid is not None:
            self.fleet = FleetTrainer.from_sweep(base_net, grid,
                                                 seed=config.seed)
            if self.fleet.n_members != n_members:
                raise ValueError(
                    f"grid implies {self.fleet.n_members} members, "
                    f"n_members says {n_members}")
        else:
            self.fleet = FleetTrainer(base_net, n_members,
                                      seed=config.seed)
        if listeners:
            self.fleet.set_listeners(*listeners)
        M = self.fleet.n_members
        self.envs = [mdp_factory(i) for i in range(M)]
        obs_dim = int(np.prod(self.envs[0].observation_space.shape))
        self.n_actions = self.envs[0].action_space.n
        self.replays = [ExpReplay(config.exp_rep_max_size, obs_dim,
                                  seed=config.seed + i) for i in range(M)]
        self._eps = [EpsGreedy(config,
                               np.random.default_rng(config.seed + i))
                     for i in range(M)]
        # per-member frozen target stack, synced every
        # target_dqn_update_freq steps (reference QLearning.setTarget)
        self._target = self.fleet.stacked_state()
        self.episode_rewards: List[List[float]] = [[] for _ in range(M)]
        self.step_count = 0

    # -- stacked Q evaluation ---------------------------------------------
    def _q_all(self, obs: np.ndarray, target: bool = False) -> np.ndarray:
        """[M, B, obs] observations → [M, B, A] Q values through ONE
        vmapped dispatch (live or frozen-target params)."""
        params = self._target if target else None
        return np.asarray(self.fleet.output(obs, params=params))

    # -- one synchronized population step ---------------------------------
    def _learn(self) -> None:
        c = self.conf
        M = self.fleet.n_members
        cols = [r.sample(c.batch_size) for r in self.replays]
        obs = np.stack([col[0] for col in cols])
        action = np.stack([col[1] for col in cols])
        reward = np.stack([col[2] for col in cols])
        nxt = np.stack([col[3] for col in cols])
        done = np.stack([col[4] for col in cols])
        q_cur = self._q_all(obs)
        q_next_t = self._q_all(nxt, target=True)
        if c.double_dqn:
            best = np.argmax(self._q_all(nxt), axis=2)
        else:
            best = np.argmax(q_next_t, axis=2)
        rows = np.arange(c.batch_size)
        next_val = np.stack([q_next_t[m, rows, best[m]] for m in range(M)])
        td = reward * c.reward_factor + c.gamma * next_val * (1 - done)
        if c.error_clamp > 0:
            cur = np.stack([q_cur[m, rows, action[m]] for m in range(M)])
            td = cur + np.clip(td - cur, -c.error_clamp, c.error_clamp)
        y = q_cur.copy()
        for m in range(M):
            y[m, rows, action[m]] = td[m]
        # non-taken actions keep their current Q -> zero gradient: the
        # reference setTarget construction, all M agents in one step
        self.fleet.step(obs.astype(np.float32), y.astype(np.float32),
                        per_member=True)

    def train(self, max_steps: Optional[int] = None) -> List[List[float]]:
        """Synchronized population loop: all M envs step together (a
        culled member's env keeps playing its frozen policy — its
        learning is what stopped). Returns per-member episode rewards."""
        c = self.conf
        M = self.fleet.n_members
        limit = max_steps if max_steps is not None else c.max_step
        obs = [env.reset() for env in self.envs]
        ep_reward = [0.0] * M
        ep_len = [0] * M
        while self.step_count < limit:
            stacked = np.stack(obs).astype(np.float32)[:, None, :]
            q = self._q_all(stacked)[:, 0, :]
            for m in range(M):
                a = self._eps[m].next_action(q[m], self.step_count,
                                             self.n_actions)
                nxt, r, done, _ = self.envs[m].step(a)
                self.replays[m].store(obs[m], a, r, nxt, done)
                ep_reward[m] += r
                ep_len[m] += 1
                if done or ep_len[m] >= c.max_epoch_step:
                    self.episode_rewards[m].append(ep_reward[m])
                    ep_reward[m] = 0.0
                    ep_len[m] = 0
                    obs[m] = self.envs[m].reset()
                else:
                    obs[m] = nxt
            self.step_count += 1
            if self.step_count >= c.update_start and \
                    all(len(r) >= c.batch_size for r in self.replays):
                self._learn()
            if self.step_count % c.target_dqn_update_freq == 0:
                self._target = self.fleet.stacked_state()
        self.fleet.drain()
        return self.episode_rewards

    # -- winners -----------------------------------------------------------
    def best_member(self) -> int:
        """Alive member with the lowest last-drained TD loss (telemetry
        bus required — attach a telemetry listener)."""
        return self.fleet.best_member()

    def policy_of(self, member: int):
        """Greedy play policy of one member (exported solo — serveable
        through ServingEngine / publish_checkpoint like any model)."""
        from .dqn import DQNPolicy

        return DQNPolicy(self.fleet.export_member(member))
