"""graftlint core: module loading, suppression accounting, rule driving.

The engine is deliberately dependency-free (stdlib ``ast`` only — no jax
import) so the whole package lints in well under a second and the lint
tests cost tier-1 milliseconds.

Model
-----
- A :class:`ModuleContext` is one parsed file: source text, AST with
  parent links, and the per-line suppression table.
- A :class:`Project` is the set of modules under the scanned root plus a
  *reference corpus* (the sibling ``tests/`` tree and ``bench.py``, when
  they exist next to the scanned root) for rules that cross-check
  non-package files without linting them.
- A :class:`Rule` sees each module (``check``) and gets one project-wide
  pass at the end (``finalize``) for cross-file invariants.

Suppressions
------------
``# graftlint: disable=<rule>[,<rule>] -- <reason>`` on the offending
line, any line the offending statement spans, or the line directly above
it. The justification after ``--`` is REQUIRED: a bare disable is itself
a finding (``bad-suppression``) and suppresses nothing. ``disable=all``
matches every rule. Suppressed findings are kept (and shown with
``--show-suppressed`` / in JSON) so the ledger of accepted risks stays
visible — and a justified suppression that matches nothing is flagged
(``unused-suppression``) when every rule it names actually ran, so
stale entries can't linger after the guarded code moves.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional

SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\-\s]+?)"
    r"(?:\s*--\s*(\S.*?))?\s*$")

# engine-emitted pseudo-rules (never suppressible)
BAD_SUPPRESSION = "bad-suppression"
UNUSED_SUPPRESSION = "unused-suppression"
PARSE_ERROR = "parse-error"


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    hint: str = ""
    end_line: Optional[int] = None
    suppressed: bool = False
    reason: str = ""

    def to_json(self) -> dict:
        out = {"rule": self.rule, "path": self.path, "line": self.line,
               "col": self.col, "message": self.message}
        if self.hint:
            out["hint"] = self.hint
        if self.suppressed:
            out["suppressed"] = True
            out["reason"] = self.reason
        return out

    def render(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}"
        tail = f"  (hint: {self.hint})" if self.hint else ""
        sup = f"  [suppressed: {self.reason}]" if self.suppressed else ""
        return f"{loc}: [{self.rule}] {self.message}{tail}{sup}"


class _Suppression:
    __slots__ = ("rules", "reason", "line", "used")

    def __init__(self, rules, reason, line):
        self.rules = rules          # set of rule names, or {"all"}
        self.reason = reason        # None → invalid (bad-suppression)
        self.line = line
        self.used = False

    def matches(self, rule: str) -> bool:
        return self.reason is not None and \
            ("all" in self.rules or rule in self.rules)


class ModuleContext:
    """One parsed source file. ``tree`` is None when the file failed to
    parse (the engine emits a parse-error finding instead of crashing the
    whole run on one bad file)."""

    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.Module] = ast.parse(text)
        except SyntaxError as e:
            self.tree = None
            self.parse_error = e
        if self.tree is not None:
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    child.graftlint_parent = node  # type: ignore[attr-defined]
        self.suppressions: Dict[int, _Suppression] = {}
        for i, line in enumerate(self.lines, start=1):
            m = SUPPRESS_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                self.suppressions[i] = _Suppression(rules, m.group(2), i)

    # -- helpers rules lean on -------------------------------------------
    def parents(self, node: ast.AST) -> Iterable[ast.AST]:
        while True:
            node = getattr(node, "graftlint_parent", None)
            if node is None:
                return
            yield node

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for p in self.parents(node):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return p
        return None

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        for p in self.parents(node):
            if isinstance(p, ast.ClassDef):
                return p
        return None

    def segment(self, node: ast.AST) -> str:
        return ast.get_source_segment(self.text, node) or ""


class Project:
    """Everything a cross-file rule can see: the scanned modules plus the
    read-only reference corpus (tests + bench next to the scanned root)."""

    def __init__(self, root: str, modules: List[ModuleContext],
                 reference_texts: Dict[str, str]):
        self.root = root
        self.modules = modules
        self.reference_texts = reference_texts

    def module_named(self, basename: str) -> Optional[ModuleContext]:
        for mod in self.modules:
            if os.path.basename(mod.path) == basename:
                return mod
        return None


class Rule:
    """Base class. ``name`` is the suppression/CLI identifier; ``hint``
    is the default fix hint attached to findings."""

    name = ""
    description = ""
    hint = ""

    def check(self, mod: ModuleContext, project: Project) -> List[Finding]:
        return []

    def finalize(self, project: Project) -> List[Finding]:
        return []

    def finding(self, mod: ModuleContext, node: ast.AST, message: str,
                hint: Optional[str] = None) -> Finding:
        return Finding(rule=self.name, path=mod.path,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0),
                       end_line=getattr(node, "end_lineno", None),
                       message=message,
                       hint=self.hint if hint is None else hint)


# -- AST spelling helpers shared by the rules ----------------------------

def dotted_name(node: ast.AST) -> str:
    """'jax.tree.map' for the func of a call, '' when not a name chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def call_name(call: ast.Call) -> str:
    return dotted_name(call.func)


def is_device_get(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and \
        call_name(node).split(".")[-1] == "device_get"


def names_in(node: ast.AST) -> List[str]:
    return [n.id for n in ast.walk(node) if isinstance(n, ast.Name)]


# -- file walking --------------------------------------------------------

def iter_py_files(root: str) -> List[str]:
    if os.path.isfile(root):
        return [root]
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        out.extend(os.path.join(dirpath, f) for f in filenames
                   if f.endswith(".py"))
    return sorted(out)


def _collect_references(root: str,
                        module_paths: List[str]) -> Dict[str, str]:
    """tests/ + bench.py living NEXT TO the scanned root (the repo
    layout), plus any scanned file that is itself a test or bench (the
    fixture layout)."""
    refs: Dict[str, str] = {}
    parent = os.path.dirname(os.path.abspath(root)) \
        if not os.path.isfile(root) else os.path.dirname(
            os.path.dirname(os.path.abspath(root)))
    for cand in (os.path.join(parent, "bench.py"),):
        if os.path.isfile(cand):
            with open(cand, encoding="utf-8") as f:
                refs[cand] = f.read()
    tests_dir = os.path.join(parent, "tests")
    if os.path.isdir(tests_dir):
        for path in iter_py_files(tests_dir):
            with open(path, encoding="utf-8") as f:
                refs[path] = f.read()
    for path in module_paths:
        base = os.path.basename(path)
        if base.startswith("test_") or base.startswith("bench"):
            with open(path, encoding="utf-8") as f:
                refs[path] = f.read()
    return refs


class LintResult:
    def __init__(self, findings: List[Finding], root: str,
                 rule_names: List[str]):
        self.root = root
        self.rule_names = rule_names
        self.findings = [f for f in findings if not f.suppressed]
        self.suppressed = [f for f in findings if f.suppressed]

    @property
    def clean(self) -> bool:
        return not self.findings

    def to_json(self) -> dict:
        return {"root": self.root, "rules": self.rule_names,
                "findings": [f.to_json() for f in self.findings],
                "suppressed": [f.to_json() for f in self.suppressed]}


def run(root: str, rules: List[Rule]) -> LintResult:
    paths = iter_py_files(root)
    modules = [ModuleContext(p, open(p, encoding="utf-8").read())
               for p in paths]
    project = Project(root, modules,
                      _collect_references(root, paths))

    findings: List[Finding] = []
    for mod in modules:
        if mod.parse_error is not None:
            findings.append(Finding(
                rule=PARSE_ERROR, path=mod.path,
                line=mod.parse_error.lineno or 1, col=0,
                message=f"file does not parse: {mod.parse_error.msg}"))
            continue
        for rule in rules:
            findings.extend(rule.check(mod, project))
    for rule in rules:
        findings.extend(rule.finalize(project))

    # apply suppressions: offending line, any line the node spans, or the
    # contiguous comment block directly above the finding (justifications
    # routinely wrap over several comment lines)
    for f in findings:
        mod = next((m for m in modules if m.path == f.path), None)
        if mod is None or f.rule in (BAD_SUPPRESSION, UNUSED_SUPPRESSION,
                                     PARSE_ERROR):
            continue
        last = f.end_line or f.line
        candidates = list(range(f.line, last + 1))
        line = f.line - 1
        while line >= 1 and f.line - line <= 12 and \
                line <= len(mod.lines) and \
                mod.lines[line - 1].lstrip().startswith("#"):
            candidates.append(line)
            line -= 1
        for line in candidates:
            sup = mod.suppressions.get(line)
            if sup is not None and sup.matches(f.rule):
                f.suppressed = True
                f.reason = sup.reason or ""
                sup.used = True
                break

    # a disable with no justification suppresses nothing and is itself a
    # finding — the whole point is that accepted risks carry a WHY; and
    # a justified suppression that matched nothing is a stale ledger
    # entry (the guarded code moved or the risk is gone) — flag it so
    # the accepted-risk list cannot silently rot
    active = {r.name for r in rules}
    for mod in modules:
        for sup in mod.suppressions.values():
            if sup.reason is None:
                findings.append(Finding(
                    rule=BAD_SUPPRESSION, path=mod.path, line=sup.line,
                    col=0,
                    message="suppression without a justification "
                            "(write: # graftlint: disable=<rule> -- "
                            "<why this is safe>)"))
            elif not sup.used and "all" not in sup.rules \
                    and sup.rules <= active:
                # judged only when every named rule actually ran — a
                # subset run (--rules x) cannot tell whether another
                # rule's suppression is stale; "all" is never judgeable
                findings.append(Finding(
                    rule=UNUSED_SUPPRESSION, path=mod.path, line=sup.line,
                    col=0,
                    message="suppression for "
                            f"{'/'.join(sorted(sup.rules))} matched no "
                            "finding — delete the stale entry or fix "
                            "the rule name"))

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(findings, root, [r.name for r in rules])


def render_human(result: LintResult, show_suppressed: bool = False) -> str:
    lines = [f.render() for f in result.findings]
    if show_suppressed:
        lines += [f.render() for f in result.suppressed]
    lines.append(f"{len(result.findings)} finding(s), "
                 f"{len(result.suppressed)} suppressed "
                 f"[{len(result.rule_names)} rules]")
    return "\n".join(lines)


def render_json(result: LintResult) -> str:
    return json.dumps(result.to_json(), indent=2)
