"""CLI: ``python -m tools.graftlint [paths...]``. Non-zero exit iff
unsuppressed findings remain (the bench preflight and CI key off it)."""

from __future__ import annotations

import argparse
import os
import sys

from . import all_rules, lint, render_human, render_json


def default_root() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "deeplearning4j_tpu")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint",
        description="AST lints for this repo's shipped bug classes")
    parser.add_argument("paths", nargs="*", default=None,
                        help="files or directories (default: the "
                             "deeplearning4j_tpu package)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output")
    parser.add_argument("--rules", default=None,
                        help="comma-separated subset of rules to run")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings with their "
                             "justifications")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.name}: {rule.description}")
        return 0

    rule_names = [r.strip() for r in args.rules.split(",")] \
        if args.rules else None
    paths = args.paths or [default_root()]
    exit_code = 0
    for path in paths:
        try:
            result = lint(path, rule_names)
        except FileNotFoundError as e:
            print(str(e), file=sys.stderr)
            return 2
        if args.as_json:
            print(render_json(result))
        else:
            print(render_human(result, show_suppressed=args.show_suppressed))
        if not result.clean:
            exit_code = 1
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
