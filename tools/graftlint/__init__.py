"""graftlint: AST lints for the bug classes this repo actually shipped.

Replaces the two ``tools/static_lint.py`` greps with a proper rule
engine. Six rules, each motivated by a fixed-and-regressed (or nearly)
bug:

==================== ===================================================
donation-alias       device_get zero-copy views kept without an owning
                     copy (PR-3/PR-6 glibc heap corruption), found by
                     dataflow — renames don't hide it
pallas-guard         pallas_call without interpret= (per call site) or a
                     backend gate (per module)
host-sync-in-step    float()/int()/.item()/np.*/print/device_get inside
                     jitted / shard_mapped / lax-loop-body functions,
                     found by decorator + call-graph walk
retrace-hazard       Python bool/int literals as traced jit args;
                     dict/list literals through jit boundaries
lock-discipline      mutation of thread-shared class attributes outside
                     `with self._lock` (profiler ledgers, inference/
                     serving pools, checkpoint writer, supervisor)
fault-site-registry  fault_point sites vs the FAULT_SITES registry vs
                     the docstring table vs test/bench drills — all four
                     must agree
==================== ===================================================

Run: ``python -m tools.graftlint [paths...] [--json] [--rules a,b]``.
Suppress: ``# graftlint: disable=<rule> -- <required justification>``.
Exit is non-zero iff unsuppressed findings remain.

The runtime half of the same discipline lives in
``deeplearning4j_tpu/common/tracecheck.py`` (the steady-state trace
sanitizer); this package is static-only and never imports jax.
"""

from . import engine
from .engine import (Finding, LintResult, ModuleContext, Project, Rule,
                     render_human, render_json, run)
from .rules import RULE_NAMES, all_rules

__all__ = ["Finding", "LintResult", "ModuleContext", "Project", "Rule",
           "RULE_NAMES", "all_rules", "engine", "lint", "render_human",
           "render_json", "run"]


def lint(root: str, rule_names=None) -> LintResult:
    """Run graftlint over ``root`` with all rules (or the named subset)."""
    import os

    if not os.path.exists(root):
        # a typo'd path must not lint as "clean" — exit-code consumers
        # (CI, the bench preflight) would silently pass without scanning
        raise FileNotFoundError(f"graftlint: no such path: {root}")
    rules = all_rules()
    if rule_names is not None:
        wanted = set(rule_names)
        unknown = wanted - {r.name for r in rules}
        if unknown:
            raise ValueError(f"unknown rule(s): {sorted(unknown)} "
                             f"(have: {RULE_NAMES})")
        rules = [r for r in rules if r.name in wanted]
    return run(root, rules)
