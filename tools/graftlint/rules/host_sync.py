"""host-sync-in-step: no host blocking inside compiled step functions.

PR 1/2's whole point: the training hot loop stays on device — a
``float()`` / ``.item()`` / ``np.*`` / ``print`` / ``jax.device_get``
inside a jitted step either forces a device→host sync per call (killing
dispatch overlap) or silently burns a traced value into a trace-time
constant. This rule finds the step functions the way the repo builds
them — a decorator / call-graph walk:

- roots: functions decorated with ``jit`` / ``shard_map`` / ``pmap``
  (bare or via ``partial``), functions passed by name to
  ``jax.jit(...)`` / ``shard_map(...)`` / ``pmap(...)``, and functions
  used as ``lax.scan`` / ``lax.while_loop`` / ``lax.fori_loop`` bodies;
- edges: calls to a name that matches a ``def`` anywhere in the module
  (the ``step -> core`` closure idiom in nn/multilayer.py, nn/graph.py,
  parallel/wrapper.py) and ``self.<method>`` calls resolved within the
  enclosing class.

Inside the marked set the rule flags host-sync constructs. ``float``/
``int`` over shape/len/constant expressions are exempt (static at trace
time); everything else is a finding.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..engine import (Finding, ModuleContext, Project, Rule, call_name,
                      dotted_name)

_TRACER_ENTRY = ("jit", "shard_map", "pmap", "pjit")
_BODY_CONSUMERS = ("scan", "while_loop", "fori_loop", "cond", "switch",
                   "custom_vjp", "checkpoint", "remat")
_NP_BASES = {"np", "numpy", "onp"}


def _func_name_of(call: ast.Call) -> str:
    return call_name(call).split(".")[-1]


def _static_conversion(arg: ast.AST) -> bool:
    """float()/int() of shapes, lens and constants folds at trace time."""
    if isinstance(arg, ast.Constant):
        return True
    for node in ast.walk(arg):
        if isinstance(node, ast.Attribute) and node.attr in ("shape",
                                                             "ndim",
                                                             "size"):
            return True
        if isinstance(node, ast.Call) and call_name(node) == "len":
            return True
    return False


class HostSyncRule(Rule):
    name = "host-sync-in-step"
    description = ("float()/int()/.item()/np.*/print/device_get inside "
                   "functions that are jitted, shard_mapped, or used as "
                   "lax loop bodies (call-graph walk)")
    hint = ("keep host conversions outside the compiled step (drain via "
            "one batched device_get per window) or use device-side jnp "
            "ops; trace-time-only constructs need a suppression saying so")

    def check(self, mod: ModuleContext, project: Project) -> List[Finding]:
        defs: Dict[str, List[ast.AST]] = {}
        methods: Dict[Tuple[str, str], ast.AST] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
                cls = mod.enclosing_class(node)
                if cls is not None:
                    methods[(cls.name, node.name)] = node

        roots: Dict[ast.AST, str] = {}   # def node -> why it's marked

        # decorated defs
        for fns in defs.values():
            for fn in fns:
                for dec in fn.decorator_list:
                    names = dotted_name(dec) if not isinstance(dec, ast.Call) \
                        else call_name(dec)
                    parts = set(names.split("."))
                    if isinstance(dec, ast.Call):
                        # partial(jax.jit, ...) / jax.jit(static_argnums=..)
                        for a in list(dec.args):
                            parts |= set(dotted_name(a).split("."))
                    if parts & set(_TRACER_ENTRY):
                        roots[fn] = f"decorated `{fn.name}`"

        # functions passed by name to jit/shard_map/scan/...
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = _func_name_of(node)
            if fname in _TRACER_ENTRY or fname in _BODY_CONSUMERS:
                for arg in node.args[:2]:
                    if isinstance(arg, ast.Name) and arg.id in defs:
                        for fn in defs[arg.id]:
                            roots.setdefault(
                                fn, f"`{fn.name}` passed to {fname}")
                    elif isinstance(arg, ast.Call) and \
                            _func_name_of(arg) == "partial":
                        for pa in arg.args:
                            if isinstance(pa, ast.Name) and pa.id in defs:
                                for fn in defs[pa.id]:
                                    roots.setdefault(
                                        fn,
                                        f"`{fn.name}` passed to {fname}")

        if not roots:
            return []

        # transitive closure over same-module calls (name + self.method)
        marked: Dict[ast.AST, str] = dict(roots)
        work = list(roots)
        while work:
            fn = work.pop()
            why = marked[fn]
            cls = mod.enclosing_class(fn)
            for node in self._own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee: Optional[List[ast.AST]] = None
                if isinstance(node.func, ast.Name) and node.func.id in defs:
                    callee = defs[node.func.id]
                elif isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == "self" and cls is not None:
                    m = methods.get((cls.name, node.func.attr))
                    callee = [m] if m is not None else None
                for c in callee or []:
                    if c not in marked:
                        marked[c] = why
                        work.append(c)

        findings: List[Finding] = []
        for fn, why in marked.items():
            findings.extend(self._scan_body(mod, fn, why))
        return findings

    def _own_nodes(self, fn: ast.AST) -> List[ast.AST]:
        """The function's nodes EXCLUDING nested def bodies (nested defs
        are marked separately when actually called)."""
        out: List[ast.AST] = []
        stack = list(fn.body)
        while stack:
            node = stack.pop()
            out.append(node)
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                stack.append(child)
        return out

    def _scan_body(self, mod: ModuleContext, fn: ast.AST,
                   why: str) -> List[Finding]:
        findings: List[Finding] = []
        where = f"in compiled step `{fn.name}` ({why})"
        for node in self._own_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            last = name.split(".")[-1]
            if name in ("float", "int") and node.args and \
                    not _static_conversion(node.args[0]):
                findings.append(self.finding(
                    mod, node,
                    f"host conversion {name}() on a traced value {where}"))
            elif last == "item" and isinstance(node.func, ast.Attribute):
                findings.append(self.finding(
                    mod, node, f".item() host sync {where}"))
            elif name == "print":
                findings.append(self.finding(
                    mod, node,
                    f"print() {where} — runs at trace time only (or "
                    "syncs if fed a traced value); use jax.debug.print"))
            elif last == "device_get":
                findings.append(self.finding(
                    mod, node, f"jax.device_get {where} — device->host "
                    "round-trip inside the compiled region"))
            elif name.split(".")[0] in _NP_BASES:
                findings.append(self.finding(
                    mod, node,
                    f"numpy call `{name}` {where} — executes on host at "
                    "trace time and freezes its result into the trace"))
        return findings
