"""fault-site-registry: no silent drift between drills, code and docs.

``common/faultinject.py`` owns a central ``FAULT_SITES`` registry (site
name -> accepted kinds + which drill uses it). This rule closes the loop
project-wide:

- every ``fault_point("site", ...)`` call site must name a registered
  site, with a LITERAL string (a computed site can't be audited);
- every registered site must have at least one ``fault_point`` call site
  in the scanned tree (a registry entry with no instrumentation is a
  drill that silently stopped existing);
- every registered site must be referenced by at least one test or bench
  file (the sibling ``tests/`` + ``bench.py`` corpus) — a site no drill
  exercises is dead documentation;
- every registered site must appear in the faultinject module docstring
  (the human-readable table is generated-checked, not trusted).

When the scanned tree has no ``FAULT_SITES`` at all the rule only
reports call sites as unregistered if a faultinject module IS present —
so linting a subpackage stays quiet, while linting the real package (or
a fixture with a mini registry) checks everything. Registry completeness
additionally requires a test/bench reference corpus in sight: a subtree
scan (even one holding a caller, like common/ with the watchtower
evaluator's fault point) has no drill corpus and must not mass-report
the package's other sites as dead.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..engine import Finding, ModuleContext, Project, Rule, call_name


def _parse_registry(mod: ModuleContext) -> Optional[Dict[str, ast.AST]]:
    """FAULT_SITES = {"site": {...}} at module level -> {site: key node}."""
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and \
                any(isinstance(t, ast.Name) and t.id == "FAULT_SITES"
                    for t in node.targets) and \
                isinstance(node.value, ast.Dict):
            out: Dict[str, ast.AST] = {}
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out[k.value] = k
            return out
    return None


class FaultSiteRegistryRule(Rule):
    name = "fault-site-registry"
    description = ("every fault_point site string registered in "
                   "common/faultinject.py FAULT_SITES, every registered "
                   "site instrumented, drilled (tests/bench) and "
                   "documented in the module docstring")
    hint = ("add the site to FAULT_SITES (name, kinds, drill) and to the "
            "faultinject docstring table; dead entries come out instead")

    def finalize(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        reg_mod = project.module_named("faultinject.py")
        registry: Optional[Dict[str, ast.AST]] = None
        if reg_mod is not None and reg_mod.tree is not None:
            registry = _parse_registry(reg_mod)

        # collect every fault_point call site in the scanned tree
        calls: List[Tuple[ModuleContext, ast.Call, Optional[str]]] = []
        for mod in project.modules:
            if mod.tree is None:
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) and \
                        call_name(node).split(".")[-1] == "fault_point":
                    site: Optional[str] = None
                    if node.args and isinstance(node.args[0], ast.Constant) \
                            and isinstance(node.args[0].value, str):
                        site = node.args[0].value
                    calls.append((mod, node, site))

        if reg_mod is None:
            return findings      # nothing to check against in this tree

        if registry is None:
            if calls:
                findings.append(Finding(
                    rule=self.name, path=reg_mod.path, line=1, col=0,
                    message="faultinject module has no FAULT_SITES "
                            "registry but fault_point sites exist",
                    hint=self.hint))
            return findings

        seen: Dict[str, int] = {}
        for mod, node, site in calls:
            if mod is reg_mod:
                continue        # the hook's own definition/docs
            if site is None:
                findings.append(self.finding(
                    mod, node,
                    "fault_point called with a non-literal site — the "
                    "registry cannot audit it",
                    hint="pass the site as a string literal"))
                continue
            seen[site] = seen.get(site, 0) + 1
            if site not in registry:
                findings.append(self.finding(
                    mod, node,
                    f"fault_point site '{site}' is not registered in "
                    "common.faultinject.FAULT_SITES"))

        # registry COMPLETENESS (every site called / documented / drilled)
        # is a whole-package property: a subtree scan that happens to
        # include faultinject.py but not the callers (e.g. linting
        # common/ alone — which DOES hold one caller, the watchtower
        # evaluator's own fault point) must not report every other site
        # as dead. Per-call checks above still ran; completeness also
        # needs the drill corpus (tests/bench) in sight, which only the
        # package root or a self-contained fixture has.
        if not seen or not project.reference_texts:
            return findings

        docstring = ast.get_docstring(reg_mod.tree) or ""
        refs = project.reference_texts
        for site, key_node in registry.items():
            f_at = lambda msg: Finding(   # noqa: E731
                rule=self.name, path=reg_mod.path,
                line=getattr(key_node, "lineno", 1),
                col=getattr(key_node, "col_offset", 0),
                message=msg, hint=self.hint)
            if site not in seen:
                findings.append(f_at(
                    f"registered fault site '{site}' has no fault_point "
                    "call site in the scanned tree"))
            if site not in docstring:
                findings.append(f_at(
                    f"registered fault site '{site}' is missing from the "
                    "faultinject module docstring table"))
            if refs and not any(site in text for text in refs.values()):
                findings.append(f_at(
                    f"registered fault site '{site}' has no test or "
                    "bench reference — no drill exercises it"))
        return findings
