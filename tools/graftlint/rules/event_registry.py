"""event-name-registry: flight-recorder event names audited end to end.

``common/flightrec.py`` owns a central ``EVENT_SITES`` registry (event
name -> description + the drill that proves it fires), mirroring
faultinject's ``FAULT_SITES``. This rule closes the same loop
project-wide for the event timeline:

- every ``flightrec.event("name", ...)`` / ``flightrec.span("name", ...)``
  call (module-attribute spelling, or the bare names when imported with
  ``from ...flightrec import event, span``) must name a registered event
  with a LITERAL string — a computed name cannot be audited;
- every registered name must be emitted somewhere in the scanned tree
  (a registry entry nothing emits is a timeline that silently stopped
  existing);
- every registered name must appear in the flightrec module docstring
  (the human-readable table is generated-checked, not trusted);
- every registered name must be referenced by at least one test or
  bench file (the sibling ``tests/`` + ``bench.py`` corpus) — an event
  no drill ever asserts on is dead observability.

Completeness (the last three checks) runs only when the scan reaches
BEYOND the registry module's own directory: a subtree scan of
``common/`` alone sees the common-owned emit sites (profiler sections,
fault firings, tracecheck violations) but not the rest of the package's,
and must not report every other subsystem's names as dead. Per-call
checks (unregistered / non-literal names) always run.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from ..engine import Finding, ModuleContext, Project, Rule, call_name

_EMIT_FUNCS = ("event", "span")


def _parse_registry(mod: ModuleContext) -> Optional[Dict[str, ast.AST]]:
    """EVENT_SITES = {"name": {...}} at module level -> {name: key node}.
    Accepts the plain and the annotated (``EVENT_SITES: Dict[...] =``)
    assignment spellings."""
    for node in mod.tree.body:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target] if isinstance(node, ast.AnnAssign)
                   else [])
        if targets and \
                any(isinstance(t, ast.Name) and t.id == "EVENT_SITES"
                    for t in targets) and \
                isinstance(getattr(node, "value", None), ast.Dict):
            out: Dict[str, ast.AST] = {}
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out[k.value] = k
            return out
    return None


def _emit_aliases(mod: ModuleContext) -> Tuple[Set[str], Dict[str, str]]:
    """(module aliases of flightrec, {bare function alias: event|span})."""
    mod_aliases: Set[str] = set()
    func_aliases: Dict[str, str] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "flightrec":
                    mod_aliases.add(alias.asname or "flightrec")
                elif (node.module or "").split(".")[-1] == "flightrec" \
                        and alias.name in _EMIT_FUNCS:
                    func_aliases[alias.asname or alias.name] = alias.name
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.split(".")[-1] == "flightrec":
                    mod_aliases.add(alias.asname
                                    or alias.name.split(".")[0])
    return mod_aliases, func_aliases


class EventNameRegistryRule(Rule):
    name = "event-name-registry"
    description = ("every flightrec.event/span name literal and "
                   "registered in common/flightrec.py EVENT_SITES; every "
                   "registered name emitted, documented in the module "
                   "docstring table and drilled (tests/bench)")
    hint = ("add the name to EVENT_SITES (desc, drill) and the flightrec "
            "docstring table; dead entries come out instead")

    def finalize(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        reg_mod = project.module_named("flightrec.py")
        if reg_mod is None or reg_mod.tree is None:
            return findings          # nothing to check against
        registry = _parse_registry(reg_mod)

        calls: List[Tuple[ModuleContext, ast.Call, Optional[str]]] = []
        for mod in project.modules:
            if mod.tree is None or mod is reg_mod:
                continue
            mod_aliases, func_aliases = _emit_aliases(mod)
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                dn = call_name(node)
                parts = dn.split(".")
                is_emit = (len(parts) >= 2 and parts[-1] in _EMIT_FUNCS
                           and parts[-2] in (mod_aliases | {"flightrec"})) \
                    or (len(parts) == 1 and dn in func_aliases)
                if not is_emit:
                    continue
                event_name: Optional[str] = None
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    event_name = node.args[0].value
                calls.append((mod, node, event_name))

        if registry is None:
            if calls:
                findings.append(Finding(
                    rule=self.name, path=reg_mod.path, line=1, col=0,
                    message="flightrec module has no EVENT_SITES registry "
                            "but event emissions exist",
                    hint=self.hint))
            return findings

        seen: Dict[str, int] = {}
        for mod, node, event_name in calls:
            if event_name is None:
                findings.append(self.finding(
                    mod, node,
                    "flight-recorder event emitted with a non-literal "
                    "name — the registry cannot audit it",
                    hint="pass the event name as a string literal"))
                continue
            seen[event_name] = seen.get(event_name, 0) + 1
            if event_name not in registry:
                findings.append(self.finding(
                    mod, node,
                    f"flight-recorder event name '{event_name}' is not "
                    "registered in common.flightrec.EVENT_SITES"))

        # registry completeness is a whole-package property — see the
        # module docstring: only judged when the scan reaches beyond the
        # registry module's own directory AND at least one emit exists
        reg_dir = os.path.dirname(os.path.abspath(reg_mod.path))
        beyond = any(
            os.path.dirname(os.path.abspath(m.path)) != reg_dir
            for m, _n, _e in calls)
        if not seen or not beyond:
            return findings

        docstring = ast.get_docstring(reg_mod.tree) or ""
        refs = project.reference_texts
        for event_name, key_node in registry.items():
            f_at = lambda msg: Finding(   # noqa: E731
                rule=self.name, path=reg_mod.path,
                line=getattr(key_node, "lineno", 1),
                col=getattr(key_node, "col_offset", 0),
                message=msg, hint=self.hint)
            if event_name not in seen:
                findings.append(f_at(
                    f"registered event '{event_name}' is never emitted "
                    "in the scanned tree"))
            if event_name not in docstring:
                findings.append(f_at(
                    f"registered event '{event_name}' is missing from "
                    "the flightrec module docstring table"))
            if refs and not any(event_name in text
                                for text in refs.values()):
                findings.append(f_at(
                    f"registered event '{event_name}' has no test or "
                    "bench reference — no drill asserts it fires"))
        return findings
