"""The graftlint rule set — one module per shipped bug class."""

from .donated_grad_escape import DonatedGradEscapeRule
from .donation_alias import DonationAliasRule
from .event_registry import EventNameRegistryRule
from .exec_census import ExecutableCensusRule
from .fault_registry import FaultSiteRegistryRule
from .host_sync import HostSyncRule
from .lock_discipline import LockDisciplineRule
from .pallas_guard import PallasGuardRule
from .retrace_hazard import RetraceHazardRule


def all_rules():
    """Fresh instances — rules may keep per-run state in finalize()."""
    return [DonationAliasRule(), PallasGuardRule(), HostSyncRule(),
            RetraceHazardRule(), LockDisciplineRule(),
            FaultSiteRegistryRule(), EventNameRegistryRule(),
            ExecutableCensusRule(), DonatedGradEscapeRule()]


RULE_NAMES = [r.name for r in all_rules()]
