"""donated-grad-escape: grads consumed by the fused epilogue stay consumed.

The backward-epilogue fusion (PR-16) hands the flat grad buckets to
``apply_flat_updater`` / ``fused_apply`` / ``_apply_fused_flat`` INSIDE
the jitted step, with params and updater state donated at the jit
boundary. On TPU the fused kernel is free to update in place — a grad
leaf read *after* the consuming call is a use-after-donate hazard: it
compiles clean on CPU, then reads freed (or already-overwritten) HBM
the first time the real donation kicks in. The shipped near-miss is the
ZeRO-1 telemetry block in parallel/wrapper.py, which reads the reduced
grad shards after the apply — safe there (the read is in-graph, so XLA
keeps the value alive) and carrying the justified suppression this rule
demands for every such read.

Flagged shape (per function scope, statement order):

    new_p, new_s = apply_flat_updater(up, flat_p, flat_g, st, it, key)
    ...
    anything_reading(flat_g)          # <- finding

The grads argument is the third positional (or the ``flat_grads`` /
``grads`` keyword) of the recognized consumers. A consume that is
itself a ``return`` statement cannot leak (nothing executes after it in
that frame) and does not taint. Taint clears when the name is rebound;
a consume inside a branch conservatively taints everything after it —
exactly the hazard once that branch executes.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from ..engine import Finding, ModuleContext, Project, Rule, call_name

# dotted-name tails that consume flat grads inside a step; the value is
# the positional index of the grads argument
_CONSUMERS = {"apply_flat_updater": 2, "fused_apply": 2,
              "_apply_fused_flat": 2}
_GRADS_KW = ("flat_grads", "grads")

# statement fields holding nested blocks (walked separately, in source
# order, with the shared taint state)
_BLOCK_FIELDS = ("body", "orelse", "finalbody")


def _consumer(call: ast.Call):
    tail = call_name(call).split(".")[-1]
    return tail if tail in _CONSUMERS else None


def _grads_arg(call: ast.Call, tail: str):
    for kw in call.keywords:
        if kw.arg in _GRADS_KW:
            return kw.value
    pos = _CONSUMERS[tail]
    return call.args[pos] if len(call.args) > pos else None


def _base_name(expr: ast.AST):
    """The identifier a grads argument resolves to: a plain name, or the
    base of a subscript/attribute chain (``g_sh[k]`` reads ``g_sh``)."""
    while isinstance(expr, (ast.Subscript, ast.Attribute, ast.Starred)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _header_nodes(stmt: ast.stmt):
    """The statement's own expression nodes — nested statement blocks
    (and nested function/class scopes) excluded; those are visited as
    blocks/scopes of their own."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return
    blocks = []
    for field in _BLOCK_FIELDS:
        blocks.extend(getattr(stmt, field, []) or [])
    for handler in getattr(stmt, "handlers", []) or []:
        blocks.extend(handler.body)
    skip = {id(n) for b in blocks for n in ast.walk(b)}
    for node in ast.walk(stmt):
        if id(node) not in skip:
            yield node


class DonatedGradEscapeRule(Rule):
    name = "donated-grad-escape"
    description = ("a grad pytree/bucket referenced after "
                   "apply_flat_updater consumed it inside a jitted step "
                   "— use-after-donate hazard once the buffers donate")
    hint = ("read everything you need from the grads BEFORE the fused "
            "apply, or keep the read in-graph and suppress with the "
            "reason; after donation the bytes are gone")

    def check(self, mod: ModuleContext, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        scopes = [mod.tree] + [
            n for n in ast.walk(mod.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for scope in scopes:
            self._walk_block(mod, list(getattr(scope, "body", [])), {},
                             findings)
        return findings

    def _walk_block(self, mod: ModuleContext, body: List[ast.stmt],
                    consumed: Dict[str, int],
                    findings: List[Finding]) -> None:
        for stmt in body:
            header = list(_header_nodes(stmt))
            # reads of already-consumed names in this statement
            for node in header:
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        node.id in consumed:
                    findings.append(self.finding(
                        mod, node,
                        f"grads {node.id!r} read after the fused epilogue "
                        f"consumed it on line {consumed[node.id]}"))
            # rebinding the name clears the taint
            for node in header:
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, (ast.Store, ast.Del)):
                    consumed.pop(node.id, None)
            # record new consumes (a return-consume cannot leak: nothing
            # executes after it in this frame)
            if not isinstance(stmt, ast.Return):
                for node in header:
                    if isinstance(node, ast.Call):
                        tail = _consumer(node)
                        if tail is None:
                            continue
                        arg = _grads_arg(node, tail)
                        name = _base_name(arg) if arg is not None else None
                        if name is not None:
                            consumed[name] = node.lineno
            # nested blocks: each branch forks the pre-state (a consume
            # in the if-body must not taint the else-body — only one
            # executes), then the post-states union into the outer taint
            # so code AFTER the statement sees the hazard of every path
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                branches = [getattr(stmt, f, None) for f in _BLOCK_FIELDS]
                branches += [h.body for h in
                             getattr(stmt, "handlers", []) or []]
                pre = dict(consumed)
                for blk in branches:
                    if not blk:
                        continue
                    state = dict(pre)
                    self._walk_block(mod, blk, state, findings)
                    consumed.update(state)
