"""lock-discipline: shared-state mutation outside the owning lock.

The supervisor/serving/checkpoint-writer tier (PRs 3-7) is genuinely
multi-threaded: the training thread, the checkpoint writer, inference
workers, the watchdog, and HTTP handlers all touch the same objects. The
repo's convention is one owning lock per shared object (``self._lock`` /
``self._cond``), held for every mutation. This rule enforces it over a
declared REGISTRY of thread-shared classes: inside their bodies, any
``self.<attr> = ...`` / ``self.<attr> += ...`` outside a
``with self.<lock>`` block (and outside ``__init__``, which runs before
publication) is a finding.

Single-writer attributes that are deliberately unlocked (a monotonic
heartbeat the watchdog reads racily, by design) are exactly what the
justified-suppression syntax is for — the reason string documents the
ownership argument right at the mutation site.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from ..engine import Finding, ModuleContext, Project, Rule

# class name -> {"locks": owning lock attrs, "allow": attrs exempt by
# design (document WHY here when adding one)}. Fixtures and future
# shared classes participate by name.
SHARED_CLASSES: Dict[str, Dict[str, Set[str]]] = {
    # profiler ledgers: bumped from the training thread, the checkpoint
    # writer, inference workers and the telemetry drain alike
    "OpProfiler": {"locks": {"_lock"}, "allow": set()},
    # flight recorder: every subsystem's threads append to the ring;
    # the ambient correlation slot is written by the supervisor while
    # the checkpoint writer reads it at event time
    "FlightRecorder": {"locks": {"_lock"}, "allow": set()},
    # executable census: dispatches land from the training thread,
    # serving workers and the checkpoint writer; analyze() runs on
    # whichever thread collects
    "ExecutableCensus": {"locks": {"_lock"}, "allow": set()},
    # inference/serving pools: worker threads + callers + health probes.
    # ServingEngine splits its locking: _exec_lock guards the AOT
    # executable cache, _lat_lock the latency ring — both are owning
    # locks in their domains
    "ParallelInference": {"locks": {"_lock"}, "allow": set()},
    "ServingEngine": {"locks": {"_lock", "_exec_lock", "_lat_lock"},
                      "allow": set()},
    # admission controller: request threads admit/complete while the
    # brownout controller moves the shed level
    "AdmissionController": {"locks": {"_lock"}, "allow": set()},
    # autoscaler: the controller thread ticks while callers read stats
    # and drills call tick() directly
    "Autoscaler": {"locks": {"_lock"}, "allow": set()},
    # vmapped-fleet trainer: the training thread swaps carried stacked
    # state per step while sinks/serving handoffs read exports and a
    # supervisor-style controller may cull/spawn — one owning lock
    "FleetTrainer": {"locks": {"_lock"}, "allow": set()},
    # checkpoint writer: training thread submits, daemon thread commits
    "CheckpointWriter": {"locks": {"_cond", "_lock"}, "allow": set()},
    "CheckpointListener": {"locks": {"_lock"}, "allow": set()},
    # supervisor heartbeats: training thread beats, watchdog reads.
    # The allowed attributes are the supervisor's DESIGNED lock-free
    # single-slot signals: written as one reference assignment (atomic
    # under the GIL), consumed at step/dispatch boundaries, and one of
    # them (_preempt_signal) is written from a signal handler where
    # taking a lock can deadlock the interrupted thread. New supervisor
    # state does NOT get a free pass — extend this set only with the
    # same ownership argument.
    "TrainingSupervisor": {"locks": {"_lock"},
                           "allow": {"_preempt_signal", "_resize_request",
                                     "_grow", "_probe_ordinal",
                                     "_old_handlers", "incarnation"}},
    "_Heartbeat": {"locks": {"_lock"}, "allow": set()},
    "_Attempt": {"locks": {"_lock"}, "allow": set()},
    # SLO watchtower: the evaluator thread ticks while HTTP handlers,
    # the supervisor hook and benches read alert states / open incidents
    "Watchtower": {"locks": {"_lock"}, "allow": set()},
    # cluster runtime: the heartbeat daemon thread beats while the main
    # thread forms/barriers/commits. commit_incarnation is single-writer
    # by protocol (rank 0's main thread claims it before any commit and
    # only that same thread reads it at commit time)
    "ClusterRuntime": {"locks": {"_lock"},
                       "allow": {"commit_incarnation"}},
}


class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = ("attribute mutation on a registered thread-shared "
                   "class outside a `with self.<lock>` block")
    hint = ("hold the owning lock for every mutation of shared state; a "
            "deliberate single-writer attribute needs a suppression "
            "naming the owning thread")

    def check(self, mod: ModuleContext, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            reg = SHARED_CLASSES.get(cls.name)
            if reg is None:
                continue
            findings.extend(self._check_class(mod, cls, reg))
        return findings

    def _check_class(self, mod: ModuleContext, cls: ast.ClassDef,
                     reg: Dict[str, Set[str]]) -> List[Finding]:
        findings: List[Finding] = []
        locks = reg["locks"]
        allow = reg["allow"]
        for node in ast.walk(cls):
            targets: List[ast.Attribute] = []
            if isinstance(node, ast.Assign):
                targets = [t for t in node.targets
                           if self._is_self_attr(t)]
            elif isinstance(node, ast.AugAssign) and \
                    self._is_self_attr(node.target):
                targets = [node.target]
            if not targets:
                continue
            fn = mod.enclosing_function(node)
            if fn is None or fn.name == "__init__":
                continue   # class body / construction happens-before
            # the mutation may live in a nested class with its own rules
            if mod.enclosing_class(node) is not cls:
                continue
            for t in targets:
                if t.attr in allow or t.attr in locks:
                    continue
                if self._under_lock(mod, node, locks):
                    continue
                findings.append(self.finding(
                    mod, node,
                    f"`self.{t.attr}` of thread-shared class "
                    f"`{cls.name}` mutated in `{fn.name}` outside "
                    f"`with self.{'/'.join(sorted(locks))}`"))
        return findings

    @staticmethod
    def _is_self_attr(node: ast.AST) -> bool:
        return isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self"

    def _under_lock(self, mod: ModuleContext, node: ast.AST,
                    locks: Set[str]) -> bool:
        for p in mod.parents(node):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False   # a nested def runs later, outside the with
            if not isinstance(p, (ast.With, ast.AsyncWith)):
                continue
            for item in p.items:
                ctx = item.context_expr
                # `with self._lock:` / `with cls._lock:` /
                # `with self._cond:` — also accept .acquire-style
                # wrappers spelled as calls on the lock attr
                if isinstance(ctx, ast.Call):
                    ctx = ctx.func
                if isinstance(ctx, ast.Attribute) and \
                        isinstance(ctx.value, ast.Name) and \
                        ctx.value.id in ("self", "cls") and \
                        ctx.attr in locks:
                    return True
        return False
