"""pallas-guard: every pallas_call site needs its escape hatches.

The ``ops/pallas_attention.py`` recipe, made a per-call-site rule: a
``pl.pallas_call`` must (a) carry an ``interpret=`` keyword AT THE CALL
so the kernel runs on the CPU test mesh through the interpreter, and
(b) live in a module that gates on the backend (``default_backend`` /
``default_mode``) so a TPU-shaped kernel never becomes the hot path on
a backend it was not built for. The old grep checked (a) per FILE — one
guarded call could shadow an unguarded one added later; this checks the
keyword on each call node.
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding, ModuleContext, Project, Rule, call_name

_GATES = ("default_backend", "default_mode")


class PallasGuardRule(Rule):
    name = "pallas-guard"
    description = ("pallas_call sites missing the interpret= escape hatch "
                   "(per call) or a backend gate (per module)")
    hint = ("thread interpret= from a jax.default_backend() != 'tpu' gate "
            "(see ops/pallas_attention.py)")

    def check(self, mod: ModuleContext, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        sites = [n for n in ast.walk(mod.tree)
                 if isinstance(n, ast.Call)
                 and call_name(n).split(".")[-1] == "pallas_call"]
        if not sites:
            return findings
        has_gate = any(g in mod.text for g in _GATES)
        for call in sites:
            kw_names = {kw.arg for kw in call.keywords}
            if "interpret" not in kw_names:
                findings.append(self.finding(
                    mod, call,
                    "pallas_call without interpret= at the call site — "
                    "the kernel cannot run on the CPU test mesh"))
            if not has_gate:
                findings.append(self.finding(
                    mod, call,
                    "pallas_call in a module with no backend gate "
                    f"({'/'.join(_GATES)}) — the kernel path is "
                    "unconditional on every backend"))
        return findings
