"""executable-census: every compiled executable is on the observatory.

``common/xprof.py`` owns the central ``EXEC_SITES`` registry (census
name -> what registers it + the drill that proves it). The performance
observatory (ISSUE 15) is only trustworthy if every ``jax.jit`` /
``.lower(...).compile()`` call site actually registers — an executable
the census cannot see is a roofline row that silently never exists.
This rule closes the loop project-wide, mirroring fault-site-registry's
4-way pattern:

- every ``jax.jit(...)`` call (plain, ``@jax.jit`` decorator, or
  ``functools.partial(jax.jit, ...)`` decorator) and every
  ``.lower(...).compile(...)`` AOT chain must sit inside a
  ``register_jit``/``register_aot`` call or share a function scope with
  one (near-site registration); deliberately uncensused executables
  (a fresh per-call jit) carry a justified suppression;
- every ``register_jit``/``register_aot``/``note_subexec`` call must
  name a REGISTERED site with a LITERAL string;
- every registered site must have at least one register call site in the
  scanned tree, appear in the xprof module docstring table, and be
  referenced by at least one test or bench file.

When the scanned tree has no ``EXEC_SITES`` registry at all (no
xprof.py in scope) the rule stays quiet — linting an unrelated subtree
or another rule's fixtures must not mass-fire.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..engine import Finding, ModuleContext, Project, Rule, call_name

_REG_FNS = {"register_jit", "register_aot", "note_subexec"}


def _parse_registry(mod: ModuleContext) -> Optional[Dict[str, ast.AST]]:
    """EXEC_SITES = {"name": {...}} at module level (annotated or plain
    assignment) -> {name: key node}."""
    for node in mod.tree.body:
        targets = ()
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = (node.target,)
        if targets and \
                any(isinstance(t, ast.Name) and t.id == "EXEC_SITES"
                    for t in targets) and \
                isinstance(node.value, ast.Dict):
            out: Dict[str, ast.AST] = {}
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out[k.value] = k
            return out
    return None


def _is_jit_call(node: ast.Call) -> bool:
    f = node.func
    return isinstance(f, ast.Attribute) and f.attr == "jit"


def _is_aot_compile(node: ast.Call) -> bool:
    """``<expr>.lower(...).compile(...)`` in one chain."""
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "compile"
            and isinstance(f.value, ast.Call)
            and isinstance(f.value.func, ast.Attribute)
            and f.value.func.attr == "lower")


def _decorated_with_jit(fn: ast.AST) -> Optional[ast.AST]:
    """The decorator node when ``fn`` is jit-decorated (bare
    ``@jax.jit``, ``@jax.jit(...)``, or ``@functools.partial(jax.jit,
    ...)``), else None."""
    for dec in getattr(fn, "decorator_list", ()):
        if isinstance(dec, ast.Attribute) and dec.attr == "jit":
            return dec
        if isinstance(dec, ast.Call):
            if _is_jit_call(dec):
                return dec
            if call_name(dec).split(".")[-1] == "partial" and dec.args \
                    and isinstance(dec.args[0], ast.Attribute) \
                    and dec.args[0].attr == "jit":
                return dec
    return None


class ExecutableCensusRule(Rule):
    name = "executable-census"
    description = ("every jax.jit / .lower().compile() call site "
                   "registered with the common.xprof executable census "
                   "(EXEC_SITES registry, docstring table and drill "
                   "corpus in 4-way agreement)")
    hint = ("wrap the jit in xprof.register_jit(\"<site>\", ...) (or "
            "register_aot for AOT executables), add the site to "
            "EXEC_SITES and the xprof docstring table, and reference it "
            "from a test or bench drill")

    def finalize(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        reg_mod = project.module_named("xprof.py")
        registry: Optional[Dict[str, ast.AST]] = None
        if reg_mod is not None and reg_mod.tree is not None:
            registry = _parse_registry(reg_mod)
        if registry is None:
            # no census registry in scope: an unrelated subtree / another
            # rule's fixture — nothing to hold executables against
            return findings

        seen: Dict[str, int] = {}
        reg_calls: List[Tuple[ModuleContext, ast.Call, Optional[str]]] = []
        for mod in project.modules:
            if mod.tree is None or mod is reg_mod:
                continue
            # register calls first: names + the near-site scopes
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) and \
                        call_name(node).split(".")[-1] in _REG_FNS:
                    lit: Optional[str] = None
                    if node.args and isinstance(node.args[0], ast.Constant) \
                            and isinstance(node.args[0].value, str):
                        lit = node.args[0].value
                    reg_calls.append((mod, node, lit))
            # unregistered compiled-executable call sites
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) and _is_jit_call(node):
                    if not self._registered(mod, node, node):
                        findings.append(self.finding(
                            mod, node,
                            "jax.jit call site is not registered with "
                            "the executable census"))
                elif isinstance(node, ast.Call) and _is_aot_compile(node):
                    if not self._registered(mod, node, node):
                        findings.append(self.finding(
                            mod, node,
                            ".lower().compile() AOT executable is not "
                            "registered with the executable census"))
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    dec = _decorated_with_jit(node)
                    # scope anchor is the DECORATED def itself: its
                    # enclosing function is the builder that must also
                    # hold the register call
                    if dec is not None and \
                            not self._registered(mod, dec, node):
                        findings.append(self.finding(
                            mod, dec,
                            f"jit-decorated function '{node.name}' is "
                            "not registered with the executable census"))

        for mod, node, lit in reg_calls:
            if lit is None:
                findings.append(self.finding(
                    mod, node,
                    "census registration with a non-literal site name — "
                    "the registry cannot audit it",
                    hint="pass the census name as a string literal"))
                continue
            seen[lit] = seen.get(lit, 0) + 1
            if lit not in registry:
                findings.append(self.finding(
                    mod, node,
                    f"census site '{lit}' is not registered in "
                    "common.xprof.EXEC_SITES"))

        # registry COMPLETENESS is a whole-package property (same guard
        # as fault-site-registry): only judged when register call sites
        # are actually in scope
        if not seen:
            return findings

        docstring = ast.get_docstring(reg_mod.tree) or ""
        refs = project.reference_texts
        for site, key_node in registry.items():
            f_at = lambda msg: Finding(   # noqa: E731
                rule=self.name, path=reg_mod.path,
                line=getattr(key_node, "lineno", 1),
                col=getattr(key_node, "col_offset", 0),
                message=msg, hint=self.hint)
            if site not in seen:
                findings.append(f_at(
                    f"registered census site '{site}' has no "
                    "register_jit/register_aot/note_subexec call site in "
                    "the scanned tree"))
            if site not in docstring:
                findings.append(f_at(
                    f"registered census site '{site}' is missing from "
                    "the xprof module docstring table"))
            if refs and not any(site in text for text in refs.values()):
                findings.append(f_at(
                    f"registered census site '{site}' has no test or "
                    "bench reference — no drill exercises it"))
        return findings

    @staticmethod
    def _registered(mod: ModuleContext, node: ast.AST,
                    scope_anchor: ast.AST) -> bool:
        """True when the call site is inside a register call, or shares
        its enclosing function scope with one (near-site registration —
        builders register the jit they just constructed)."""
        for p in mod.parents(node):
            if isinstance(p, ast.Call) and \
                    call_name(p).split(".")[-1] in _REG_FNS:
                return True
        fn = mod.enclosing_function(scope_anchor)
        scope = fn if fn is not None else mod.tree
        for n in ast.walk(scope):
            if isinstance(n, ast.Call) and \
                    call_name(n).split(".")[-1] in _REG_FNS:
                return True
        return False
