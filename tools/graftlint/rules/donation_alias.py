"""donation-alias: device_get views must be copied before they are kept.

The PR-3 / PR-6 heap-corruption class: on the CPU backend
``jax.device_get`` returns ZERO-COPY views of device buffers, and
``np.asarray`` of such a view is still the same memory. Hand the view
into (or stash it across) a ``donate_argnums`` step and the next
dispatch frees the bytes under the reader — observed as glibc heap
corruption, twice. The grep lint caught the two literal spellings; this
rule follows the dataflow, so a view laundered through a rename

    host = jax.device_get(params)
    ...
    arr = np.asarray(host[0])          # still the same device bytes

is a finding too. Flagged shapes (per function scope, statement order):

- ``np.asarray(<device_get or tainted name>)``
- ``<tree>.map(np.asarray, <device_get or tainted name>)``
- ``self.<attr> = <device_get call>`` / ``x[k] = <device_get call>`` —
  the result escapes the statement with no owning copy at all

Taint propagates through plain renames, tuple unpacking and ``for``
targets whose iterable is tainted; it clears when the name is rebound to
anything else (``np.array(...)`` of a view is an owning copy).
"""

from __future__ import annotations

import ast
from typing import Dict, List

from ..engine import (Finding, ModuleContext, Project, Rule, call_name,
                      is_device_get)

_NP_BASES = {"np", "numpy", "onp"}


def _is_np_asarray(node: ast.AST) -> bool:
    return isinstance(node, ast.Attribute) and node.attr == "asarray" and \
        isinstance(node.value, ast.Name) and node.value.id in _NP_BASES


def _is_tree_map(call: ast.Call) -> bool:
    name = call_name(call)
    return (name.endswith(".map") and "tree" in name) or \
        name.split(".")[-1] in ("tree_map", "tree_multimap")


class DonationAliasRule(Rule):
    name = "donation-alias"
    description = ("dataflow from jax.device_get into np.asarray or a "
                   "bare escaping assignment — a zero-copy view kept "
                   "without an owning copy")
    hint = ("copy before you keep: np.array(...) / jax.tree.map(np.array, "
            "...) — device_get views alias donatable buffers "
            "(PR-3/PR-6 heap corruption)")

    def check(self, mod: ModuleContext, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        scopes = [mod.tree] + [
            n for n in ast.walk(mod.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for scope in scopes:
            findings.extend(self._check_scope(mod, scope))
        return findings

    # -- one lexical scope, statements in source order -------------------
    def _check_scope(self, mod: ModuleContext,
                     scope: ast.AST) -> List[Finding]:
        findings: List[Finding] = []
        tainted: Dict[str, int] = {}    # name -> line it was tainted at

        def is_tainted(expr: ast.AST) -> bool:
            if is_device_get(expr):
                return True
            if isinstance(expr, ast.Name):
                return expr.id in tainted
            if isinstance(expr, (ast.Subscript, ast.Attribute, ast.Starred)):
                return is_tainted(expr.value)
            if isinstance(expr, ast.Call):
                # <tainted>.items() / enumerate(<tainted>) / zip(...)
                fn = expr.func
                if isinstance(fn, ast.Attribute) and is_tainted(fn.value):
                    return True
                if call_name(expr) in ("enumerate", "zip", "iter",
                                      "reversed", "list", "tuple"):
                    return any(is_tainted(a) for a in expr.args)
            return False

        def _bound_names(target: ast.AST):
            """Names BOUND by an assignment target. Attribute/subscript
            targets bind nothing — `self.x = ...` must neither taint nor
            clear `self` (the base object is not the assigned value)."""
            if isinstance(target, ast.Name):
                yield target.id
            elif isinstance(target, ast.Starred):
                yield from _bound_names(target.value)
            elif isinstance(target, (ast.Tuple, ast.List)):
                for elt in target.elts:
                    yield from _bound_names(elt)

        def taint_target(target: ast.AST, line: int) -> None:
            for name in _bound_names(target):
                tainted[name] = line

        def clear_target(target: ast.AST) -> None:
            for name in _bound_names(target):
                tainted.pop(name, None)

        def scan_expr(expr: ast.AST) -> None:
            """Flag alias-producing calls anywhere inside ``expr``
            (expressions have no nested statement scopes to double-count;
            lambdas close over the same taint environment)."""
            if expr is None:
                return
            for node in ast.walk(expr):
                if not isinstance(node, ast.Call):
                    continue
                if _is_np_asarray(node.func) and node.args and \
                        is_tainted(node.args[0]):
                    findings.append(self.finding(
                        mod, node,
                        "np.asarray over a jax.device_get result keeps a "
                        "zero-copy view of a device buffer"))
                elif _is_tree_map(node) and len(node.args) >= 2 and \
                        _is_np_asarray(node.args[0]) and \
                        any(is_tainted(a) for a in node.args[1:]):
                    findings.append(self.finding(
                        mod, node,
                        "tree.map(np.asarray, ...) over a jax.device_get "
                        "result keeps zero-copy views of device buffers"))

        def visit_stmt(stmt: ast.AST) -> None:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return  # nested scopes are scanned separately
            if isinstance(stmt, ast.Assign):
                scan_expr(stmt.value)
                self._check_assign(mod, stmt, stmt.targets, stmt.value,
                                   is_tainted, taint_target, clear_target,
                                   findings)
            elif isinstance(stmt, ast.AnnAssign):
                scan_expr(stmt.value)
                if stmt.value is not None:
                    self._check_assign(mod, stmt, [stmt.target], stmt.value,
                                       is_tainted, taint_target,
                                       clear_target, findings)
            elif isinstance(stmt, ast.AugAssign):
                scan_expr(stmt.value)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                scan_expr(stmt.iter)
                if is_tainted(stmt.iter):
                    taint_target(stmt.target, stmt.lineno)
                else:
                    clear_target(stmt.target)
                for s in stmt.body + stmt.orelse:
                    visit_stmt(s)
            elif isinstance(stmt, (ast.If, ast.While)):
                scan_expr(stmt.test)
                for s in stmt.body + stmt.orelse:
                    visit_stmt(s)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    scan_expr(item.context_expr)
                for s in stmt.body:
                    visit_stmt(s)
            elif isinstance(stmt, ast.Try):
                for s in (stmt.body + stmt.orelse + stmt.finalbody
                          + [h2 for h in stmt.handlers for h2 in h.body]):
                    visit_stmt(s)
            else:
                # Expr, Return, Raise, Assert, Delete, ... — flat scan
                scan_expr(stmt)

        body = scope.body if hasattr(scope, "body") else []
        for stmt in body:
            visit_stmt(stmt)
        return findings

    def _check_assign(self, mod, stmt, targets, value, is_tainted,
                      taint_target, clear_target, findings) -> None:
        escaping = [t for t in targets
                    if isinstance(t, (ast.Attribute, ast.Subscript))]
        if escaping and is_device_get(value):
            findings.append(self.finding(
                mod, stmt,
                "jax.device_get result stored on "
                f"`{mod.segment(escaping[0])}` with no owning copy — "
                "the view outlives the statement"))
        if is_tainted(value):
            for t in targets:
                taint_target(t, stmt.lineno)
        else:
            for t in targets:
                clear_target(t)
