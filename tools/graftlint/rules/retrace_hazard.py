"""retrace-hazard: hash-unstable Python values crossing jit boundaries.

The PR-1/PR-2 failure class in its call-site form: a Python ``bool`` /
``int`` literal handed to a jitted function is a TRACED argument — every
distinct value is a fresh trace and an XLA compile (the repo's own
steady-state contract is one compile per fit config). A ``dict`` /
``list`` literal crossing the boundary is a fresh container each call
whose leaves are Python scalars — same hazard, plus weak-ref cache
misses. Either the value is genuinely dynamic (then it should be a
device array) or it is configuration (then it belongs in
``static_argnums`` / ``static_argnames`` or a closure).

The rule resolves jitted callables module-locally: names bound via
``f = jax.jit(g, ...)``, ``self._step = jax.jit(...)`` attributes
(checked within the binding class), and defs decorated with ``jit``.
``static_argnums`` / ``static_argnames`` on the binding are honored —
a literal in a static slot is exactly right and stays quiet.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..engine import (Finding, ModuleContext, Project, Rule, call_name,
                      dotted_name)


def _static_slots(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            if isinstance(kw.value, ast.Constant) and \
                    isinstance(kw.value.value, int):
                nums.add(kw.value.value)
            elif isinstance(kw.value, (ast.Tuple, ast.List)):
                for e in kw.value.elts:
                    if isinstance(e, ast.Constant) and \
                            isinstance(e.value, int):
                        nums.add(e.value)
        elif kw.arg == "static_argnames":
            if isinstance(kw.value, ast.Constant) and \
                    isinstance(kw.value.value, str):
                names.add(kw.value.value)
            elif isinstance(kw.value, (ast.Tuple, ast.List)):
                for e in kw.value.elts:
                    if isinstance(e, ast.Constant) and \
                            isinstance(e.value, str):
                        names.add(e.value)
    return nums, names


class _Jitted:
    __slots__ = ("static_nums", "static_names", "label")

    def __init__(self, static_nums, static_names, label):
        self.static_nums = static_nums
        self.static_names = static_names
        self.label = label


class RetraceHazardRule(Rule):
    name = "retrace-hazard"
    description = ("Python bool/int literals threaded as traced jit args "
                   "where static_argnums or a closure is intended; "
                   "dict/list literals crossing jit boundaries")
    hint = ("every distinct Python value is a fresh trace+compile: mark "
            "config args static (static_argnums/static_argnames), close "
            "over them, or pass a device array for genuinely dynamic "
            "values")

    def check(self, mod: ModuleContext, project: Project) -> List[Finding]:
        jitted_names: Dict[str, _Jitted] = {}
        jitted_attrs: Dict[Tuple[str, str], _Jitted] = {}

        def is_jit_call(call: ast.Call) -> bool:
            return call_name(call).split(".")[-1] in ("jit", "pjit")

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    is_jit_call(node.value):
                nums, names = _static_slots(node.value)
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        jitted_names[t.id] = _Jitted(nums, names, t.id)
                    elif isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        cls = mod.enclosing_class(node)
                        if cls is not None:
                            jitted_attrs[(cls.name, t.attr)] = _Jitted(
                                nums, names, f"self.{t.attr}")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    dec_call = dec if isinstance(dec, ast.Call) else None
                    dec_name = call_name(dec_call) if dec_call \
                        else dotted_name(dec)
                    if dec_name.split(".")[-1] in ("jit", "pjit"):
                        nums, names = _static_slots(dec_call) \
                            if dec_call else (set(), set())
                        jitted_names[node.name] = _Jitted(
                            nums, names, node.name)

        if not jitted_names and not jitted_attrs:
            return []

        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            target: Optional[_Jitted] = None
            if isinstance(node.func, ast.Name):
                target = jitted_names.get(node.func.id)
            elif isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "self":
                cls = mod.enclosing_class(node)
                if cls is not None:
                    target = jitted_attrs.get((cls.name, node.func.attr))
            if target is None:
                continue
            findings.extend(self._check_call(mod, node, target))
        return findings

    def _check_call(self, mod: ModuleContext, call: ast.Call,
                    target: _Jitted) -> List[Finding]:
        findings: List[Finding] = []
        for pos, arg in enumerate(call.args):
            if pos in target.static_nums:
                continue
            findings.extend(self._check_arg(
                mod, arg, f"positional arg {pos}", target))
        for kw in call.keywords:
            if kw.arg is None or kw.arg in target.static_names:
                continue
            findings.extend(self._check_arg(
                mod, kw.value, f"keyword arg `{kw.arg}`", target))
        return findings

    def _check_arg(self, mod: ModuleContext, arg: ast.AST, slot: str,
                   target: _Jitted) -> List[Finding]:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, bool):
            return [self.finding(
                mod, arg,
                f"Python bool literal as traced {slot} of jitted "
                f"`{target.label}` — flips retrace the whole step")]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, int):
            return [self.finding(
                mod, arg,
                f"Python int literal as traced {slot} of jitted "
                f"`{target.label}` — every distinct value is a fresh "
                "compile")]
        if isinstance(arg, (ast.Dict, ast.List, ast.DictComp,
                            ast.ListComp)):
            kind = "dict" if isinstance(arg, (ast.Dict, ast.DictComp)) \
                else "list"
            return [self.finding(
                mod, arg,
                f"{kind} literal crosses the jit boundary as {slot} of "
                f"`{target.label}` — Python leaves inside retrace on "
                "every value change")]
        return []
