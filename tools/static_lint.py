"""Static lints for the two failure classes this repo has actually
shipped (and fixed) twice.

**Donation aliasing** (the PR-3 / PR-6 heap-corruption class):
``jax.device_get`` may return ZERO-COPY views of device buffers on the
CPU backend, and ``np.asarray`` of such a view is still the same memory
— hand either into a ``donate_argnums`` jit (or stash it across a step
that donates) and the next dispatch frees the bytes under the reader:
observed as glibc heap corruption, twice. The package-wide rule is
therefore *copy before you keep*: ``np.array`` / ``jnp.asarray``-onto-
device for anything coming out of ``device_get``. This lint greps the
package for the two alias spellings (``np.asarray(jax.device_get(...)``
and ``tree.map(np.asarray, jax.device_get(...)``) so the pattern cannot
quietly return.

**Unguarded Pallas kernels**: every ``pl.pallas_call`` site must carry
an ``interpret=`` escape hatch and a backend gate (``default_backend``
/ ``default_mode``) so the kernel (a) runs on the CPU test mesh through
the interpreter and (b) never becomes the hot path on a backend it was
not built for — the ``ops/pallas_attention.py`` recipe, made a rule.

Run as a script (non-zero exit on findings) or through
``tests/test_lint.py``, which wires both lints into tier-1 CI.
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Tuple

# spellings of "alias a device_get view instead of copying it";
# whitespace-tolerant so a line wrap does not hide a finding
_ALIAS_PATTERNS = [
    re.compile(r"np\s*\.\s*asarray\s*\(\s*jax\s*\.\s*device_get"),
    re.compile(r"tree\s*\.\s*map\s*\(\s*np\s*\.\s*asarray\s*,\s*"
               r"jax\s*\.\s*device_get"),
]

_PALLAS_CALL = re.compile(r"\bpallas_call\s*\(")
_PALLAS_GUARDS = ("interpret", "default_backend", "default_mode")


def _py_files(root: str) -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        out.extend(os.path.join(dirpath, f) for f in filenames
                   if f.endswith(".py"))
    return sorted(out)


def _lineno(text: str, pos: int) -> int:
    return text.count("\n", 0, pos) + 1


def lint_donation_aliases(root: str) -> List[Tuple[str, int, str]]:
    """(path, line, match) for every device_get-view alias in ``root``."""
    findings = []
    for path in _py_files(root):
        with open(path, encoding="utf-8") as f:
            text = f.read()
        for pat in _ALIAS_PATTERNS:
            for m in pat.finditer(text):
                findings.append((path, _lineno(text, m.start()),
                                 " ".join(m.group(0).split())))
    return findings


def lint_pallas_guards(root: str) -> List[Tuple[str, int, str]]:
    """(path, line, reason) for every ``pallas_call`` site in a file that
    lacks the interpret escape hatch or the backend gate."""
    findings = []
    for path in _py_files(root):
        with open(path, encoding="utf-8") as f:
            text = f.read()
        sites = list(_PALLAS_CALL.finditer(text))
        if not sites:
            continue
        missing = [g for g in _PALLAS_GUARDS if g not in text]
        # interpret= must appear; EITHER backend gate spelling suffices
        missing = [g for g in missing
                   if g == "interpret" or
                   not ({"default_backend", "default_mode"} - set(missing))]
        if missing:
            for m in sites:
                findings.append((path, _lineno(text, m.start()),
                                 f"pallas_call without {'/'.join(missing)} "
                                 "guard (see ops/pallas_attention.py)"))
    return findings


def package_root() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "deeplearning4j_tpu")


def main() -> int:
    root = sys.argv[1] if len(sys.argv) > 1 else package_root()
    findings = [("donation-alias", *f) for f in lint_donation_aliases(root)]
    findings += [("pallas-guard", *f) for f in lint_pallas_guards(root)]
    for kind, path, line, detail in findings:
        print(f"{path}:{line}: [{kind}] {detail}")
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
