"""Compatibility shim over ``tools/graftlint`` (the PR-8 grep lints,
now AST rules).

The two original checks — donation aliasing and unguarded Pallas
kernels — live on as graftlint's ``donation-alias`` and ``pallas-guard``
rules, alongside four more (host-sync-in-step, retrace-hazard,
lock-discipline, fault-site-registry). This module keeps the original
surface working:

- ``lint_donation_aliases(root)`` / ``lint_pallas_guards(root)`` return
  the same ``(path, line, detail)`` tuples they always did, but are now
  AST-backed — the dataflow version also catches renamed-variable
  aliases the greps could not see;
- ``python tools/static_lint.py [root]`` runs the FULL graftlint rule
  set and keeps the non-zero-exit-on-findings contract.

New code should call ``python -m tools.graftlint`` directly.
"""

from __future__ import annotations

import os
import sys
from typing import List, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:      # script invocation: tools/ is sys.path[0]
    sys.path.insert(0, _REPO_ROOT)

from tools import graftlint  # noqa: E402


def _rule_findings(root: str, rule: str) -> List[Tuple[str, int, str]]:
    result = graftlint.lint(root, rule_names=[rule])
    # suppressed findings carry a written justification — the legacy
    # callers (tests asserting "package clean") must not re-flag them
    return [(f.path, f.line, f.message) for f in result.findings]


def lint_donation_aliases(root: str) -> List[Tuple[str, int, str]]:
    """(path, line, detail) for every device_get-view alias in ``root``."""
    return _rule_findings(root, "donation-alias")


def lint_pallas_guards(root: str) -> List[Tuple[str, int, str]]:
    """(path, line, detail) for every ``pallas_call`` site missing the
    interpret escape hatch or the backend gate."""
    return _rule_findings(root, "pallas-guard")


def package_root() -> str:
    return os.path.join(_REPO_ROOT, "deeplearning4j_tpu")


def main() -> int:
    root = sys.argv[1] if len(sys.argv) > 1 else package_root()
    from tools.graftlint.__main__ import main as graftlint_main

    return graftlint_main([root])


if __name__ == "__main__":
    raise SystemExit(main())
