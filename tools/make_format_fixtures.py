#!/usr/bin/env python
"""Generate the frozen serde fixtures for the format-stability suite.

Reference: deeplearning4j ``deeplearning4j-core`` regressiontest package —
models serialized by OLD releases are committed as resources and every later
release must keep loading them (SURVEY.md §4.4, §7.3.8).

Run ONCE when a format version is introduced:

    python tools/make_format_fixtures.py

Outputs land in ``tests/resources/serde/v<N>/`` where <N> bumps only when a
container format version bumps. The directory is APPEND-ONLY: committed
fixture bytes are never regenerated or edited — a load-path change that
breaks them is a compatibility regression, not a fixture problem (see
tests/resources/serde/README.md). Expected activations are computed at
generation time and stored beside the models, so the parity check is against
frozen bytes, not re-derivation.
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

# fixtures are generated on the CPU backend for cross-machine determinism
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                   "tests", "resources", "serde", "v1")


def make_mln(out):
    from deeplearning4j_tpu.data import DataSet
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import layers as L

    conf = (NeuralNetConfiguration.builder()
            .seed(12345)
            .updater(Adam(learning_rate=0.01))
            .activation("tanh")
            .list()
            .layer(L.DenseLayer(n_out=8))
            .layer(L.OutputLayer(n_out=3, loss="mcxent",
                                 activation="softmax"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    model = MultiLayerNetwork(conf)
    model.init()
    rng = np.random.RandomState(0)
    x = rng.randn(16, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)]
    model.fit(DataSet(x, y), epochs=3)       # real updater state
    model.save(os.path.join(out, "mln.zip"), save_updater=True)
    probe = rng.randn(5, 4).astype(np.float32)
    np.savez(os.path.join(out, "mln_expected.npz"), probe=probe,
             output=model.output(probe).to_numpy())


def make_cg(out):
    from deeplearning4j_tpu.data import DataSet
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.nn import (ComputationGraph,
                                       ComputationGraphConfiguration,
                                       InputType, NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import layers as L

    conf = (ComputationGraphConfiguration
            .graph_builder(NeuralNetConfiguration.builder()
                           .seed(7).updater(Adam(0.05)).activation("tanh"))
            .add_inputs("in")
            .add_layer("dense", L.DenseLayer(n_out=8), "in")
            .add_layer("out", L.OutputLayer(n_out=3), "dense")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(4))
            .build())
    model = ComputationGraph(conf)
    model.init()
    rng = np.random.RandomState(1)
    x = rng.randn(16, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)]
    model.fit(DataSet(x, y), epochs=3)
    model.save(os.path.join(out, "cg.zip"), save_updater=True)
    probe = rng.randn(5, 4).astype(np.float32)
    np.savez(os.path.join(out, "cg_expected.npz"), probe=probe,
             output=model.output(probe)[0].to_numpy())


def make_samediff(out):
    from deeplearning4j_tpu.autodiff.samediff import SameDiff

    rng = np.random.RandomState(2)
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 3))
    w = sd.var("w", init=rng.randn(3, 4).astype(np.float32))
    b = sd.var("b", shape=(4,), init="zeros")
    sd.math.sigmoid((x @ w) + b).rename("out")
    sd.save(os.path.join(out, "samediff.sdz"))
    probe = rng.randn(4, 3).astype(np.float32)
    np.savez(os.path.join(out, "samediff_expected.npz"), probe=probe,
             output=sd.output({"x": probe}, ["out"])["out"].to_numpy())


def make_samediff_controlflow(out):
    from deeplearning4j_tpu.autodiff.samediff import SameDiff

    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(2,))
    pred = sd.math.greater(x.sum(), 0.0)
    branched = sd.cond(pred,
                       lambda s, a: s.math.multiply(a, 2.0),
                       lambda s, a: s.math.multiply(a, -1.0),
                       x, name="branchy")
    sd.while_loop(lambda s, v: s.math.less(v.sum(), 20.0),
                  lambda s, v: s.math.multiply(v, 2.0),
                  branched, name="doubler").rename("final")
    sd.save(os.path.join(out, "samediff_controlflow.sdz"))
    pos = np.asarray([1.0, 2.0], np.float32)
    neg = np.asarray([-1.0, -2.0], np.float32)
    np.savez(
        os.path.join(out, "samediff_controlflow_expected.npz"),
        pos=pos, neg=neg,
        out_pos=sd.output({"x": pos}, ["final"])["final"].to_numpy(),
        out_neg=sd.output({"x": neg}, ["final"])["final"].to_numpy())


def make_word2vec(out):
    from deeplearning4j_tpu.nlp import (Word2Vec, write_word2vec_model,
                                        write_word_vectors)

    rng = np.random.default_rng(5)
    sents = []
    for i in range(400):
        c = "cat" if i % 2 == 0 else "dog"
        sents.append(" ".join(f"{c}{j}" for j in rng.integers(0, 12, 10)))
    w = Word2Vec(min_word_frequency=3, layer_size=16, negative=3, epochs=2,
                 batch_size=256, seed=9)
    w.set_sentence_iterator(sents)
    w.fit()
    write_word2vec_model(w, os.path.join(out, "word2vec_model.zip"))
    write_word_vectors(w, os.path.join(out, "vectors.txt"), binary=False)
    write_word_vectors(w, os.path.join(out, "vectors.bin"), binary=True)
    words = sorted(w.vocab.words())[:8]
    np.savez(os.path.join(out, "word2vec_expected.npz"),
             words=np.asarray(words),
             vectors=np.stack([w.get_word_vector(wd) for wd in words]))


def main():
    os.makedirs(OUT, exist_ok=True)
    make_mln(OUT)
    make_cg(OUT)
    make_samediff(OUT)
    make_samediff_controlflow(OUT)
    make_word2vec(OUT)
    from deeplearning4j_tpu.autodiff import samediff as sd_mod
    from deeplearning4j_tpu.nlp import serializer as nlp_ser
    manifest = {
        "generated_with": {
            "model_serializer_format": 1,
            "samediff_format": sd_mod._FORMAT_VERSION,
            "word2vec_format": nlp_ser._FORMAT_VERSION,
        },
        "policy": "append-only: never regenerate committed fixtures; new "
                  "format versions add a new vN directory",
    }
    with open(os.path.join(OUT, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2)
    print("fixtures written to", os.path.abspath(OUT))


if __name__ == "__main__":
    main()
