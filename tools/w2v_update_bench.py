#!/usr/bin/env python
"""Word2Vec table-update lowering shootout at large vocab (round-3 item 2).

Measures, fence-free (rep differencing), the per-round cost of updating a
[V, D] table with B*(1+K) gradient rows:

  dense    one-hot bf16 MXU matmul accumulated into f32 (current ≤32k path)
  scatter  Array.at[idx].add with duplicates (current >32k path)
  sorted   sort idx + in-round segment dedupe, then unique-indices scatter

Usage: python tools/w2v_update_bench.py --vocab 100000
"""
import argparse
import json
import statistics
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp


def timed(fn, args, reps_lo=4, reps_hi=12):
    """Fence-free per-call time: difference chained rep counts."""

    def chain(n):
        jfn = jax.jit(lambda t, i, g, n=n: _chain(fn, t, i, g, n))
        out = jfn(*args)
        _ = float(jnp.sum(out[:64].astype(jnp.float32)))  # warm + fence
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            out = jfn(*args)
            _ = float(jnp.sum(out[:64].astype(jnp.float32)))
            times.append(time.perf_counter() - t0)
        return statistics.median(times)

    t_lo, t_hi = chain(reps_lo), chain(reps_hi)
    return max((t_hi - t_lo) / (reps_hi - reps_lo), 1e-9)


def _chain(fn, table, idx, grads, n):
    for i in range(n):
        # rotate indices so reps aren't folded away
        table = fn(table, (idx + i) % table.shape[0], grads)
    return table


def upd_dense(table, idx, grads):
    onehot = jax.nn.one_hot(idx, table.shape[0], dtype=jnp.bfloat16)
    return table + jnp.einsum("nv,nd->vd", onehot, grads.astype(jnp.bfloat16),
                              preferred_element_type=table.dtype)


def upd_scatter(table, idx, grads):
    return table.at[idx].add(grads)


def upd_sorted(table, idx, grads):
    """Sort rows, combine duplicate indices with a segment-style pass, then
    scatter with unique_indices=True (duplicates carry zero after combine)."""
    order = jnp.argsort(idx)
    si = idx[order]
    sg = grads[order]
    # suffix-cumsum trick: cumsum rows, take boundary differences => the sum
    # of each equal-index run lands on the run's LAST row
    cs = jnp.cumsum(sg, axis=0)
    is_last = jnp.concatenate([si[1:] != si[:-1], jnp.array([True])])
    # propagate previous run-boundary cumsum forward via cummax over masked
    # boundary positions
    bmark = jnp.where(is_last, jnp.arange(si.shape[0]), -1)
    prev_boundary = jnp.concatenate(
        [jnp.full((1,), -1, bmark.dtype),
         jax.lax.cummax(bmark)[:-1]])
    prev_cs = jnp.where(prev_boundary[:, None] >= 0,
                        cs[jnp.maximum(prev_boundary, 0)], 0)
    combined = jnp.where(is_last[:, None], cs - prev_cs, 0)
    # route duplicates (non-last rows) to a scratch row = V (table padded)
    tgt = jnp.where(is_last, si, table.shape[0])
    padded = jnp.concatenate([table, jnp.zeros((1, table.shape[1]),
                                               table.dtype)])
    padded = padded.at[tgt].add(combined, unique_indices=True)
    return padded[:-1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=100_000)
    ap.add_argument("--dim", type=int, default=100)
    ap.add_argument("--rows", type=int, default=8192 * 6)
    args = ap.parse_args()

    rng = np.random.RandomState(0)
    table = jnp.asarray(rng.randn(args.vocab, args.dim).astype(np.float32))
    # zipf-flavored duplicates like real negative sampling
    idx = jnp.asarray((rng.zipf(1.3, args.rows) % args.vocab).astype(np.int32))
    grads = jnp.asarray(rng.randn(args.rows, args.dim).astype(np.float32) * 1e-3)

    out = {"vocab": args.vocab, "dim": args.dim, "rows": args.rows}
    for name, fn in [("dense", upd_dense), ("scatter", upd_scatter),
                     ("sorted", upd_sorted)]:
        try:
            t = timed(fn, (table, idx, grads))
            out[name + "_ms"] = round(t * 1e3, 3)
            out[name + "_rows_per_sec"] = round(args.rows / t)
        except Exception as e:
            out[name + "_error"] = str(e)[:120]
    # correctness cross-check on small data
    st = jnp.zeros((50, 4))
    si = jnp.asarray(np.array([1, 3, 1, 49, 3, 3], np.int32))
    sg = jnp.asarray(np.arange(24, dtype=np.float32).reshape(6, 4))
    ref = np.zeros((50, 4), np.float32)
    for i, g in zip(np.asarray(si), np.asarray(sg)):
        ref[i] += g
    got = np.asarray(upd_sorted(st, si, sg))
    out["sorted_correct"] = bool(np.allclose(got, ref, atol=1e-5))
    print(json.dumps(out))


if __name__ == "__main__":
    main()
