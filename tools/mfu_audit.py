#!/usr/bin/env python
"""ResNet-50 MFU audit (round-3 verdict item 1).

Measures a MINIMAL hand-rolled ResNet-50 train step in raw jax — same math as
the zoo model (bottleneck v1, BN training mode, Nesterov momentum + L2) — with
two knobs the framework stack currently hard-codes:

  --layout {NHWC,NCHW}   activation layout (framework today: NCHW everywhere)
  --params {f32,bf16}    parameter storage dtype (framework today: fp32 with
                         per-step bf16 casts)

Purpose: isolate how much of the framework's 25% MFU is layout/dtype (fixable
in the framework) vs relay/XLA ceiling (not). Timing methodology == bench.py
(value-fenced chunks); FLOPs from XLA cost analysis of the compiled step.

Also reports transpose/convert op counts in the optimized HLO so the layout
hypothesis is checked against the compiler's actual output, not guessed.

Usage: python tools/mfu_audit.py --layout NHWC --params bf16 [--batch 128]
"""
import argparse
import json
import re
import sys
import time
import statistics
from functools import partial

import numpy as np

sys.path.insert(0, ".")
from bench import _timed_steps, CHUNK, TPU_BF16_PEAK_TFLOPS  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402


def conv(x, w, stride, padding, layout):
    if layout == "NHWC":
        dn = lax.conv_dimension_numbers(x.shape, w.shape, ("NHWC", "HWIO", "NHWC"))
    else:
        dn = lax.conv_dimension_numbers(x.shape, w.shape, ("NCHW", "OIHW", "NCHW"))
    return lax.conv_general_dilated(x, w, window_strides=stride, padding=padding,
                                    dimension_numbers=dn)


def bn_train(x, gamma, beta, layout, eps=1e-5):
    axes = (0, 1, 2) if layout == "NHWC" else (0, 2, 3)
    mean = jnp.mean(x.astype(jnp.float32), axis=axes)
    var = jnp.var(x.astype(jnp.float32), axis=axes)
    shape = (1, 1, 1, -1) if layout == "NHWC" else (1, -1, 1, 1)
    inv = lax.rsqrt(var + eps).reshape(shape).astype(x.dtype)
    mean = mean.reshape(shape).astype(x.dtype)
    return (x - mean) * inv * gamma.reshape(shape) + beta.reshape(shape)


# ---- fused BN: minimum activation passes --------------------------------
# Forward: ONE variadic reduce computes (sum, sum_sq) reading x once.
# Backward: ONE variadic reduce computes (sum dy, sum dy*xhat) reading dy,x
# once; then one elementwise pass for dx. The naive autodiff version above
# costs ~2 reduce passes fwd + ~3 passes bwd; the profiler shows those
# reduces are 46% of the resnet50 step.

def _moments_1pass(x, axes):
    """E[x], Var[x] via SIBLING reductions sharing one input: XLA's fusion
    pass merges sibling reduces into one multi-output fusion = one read of x.
    (jnp.var's (x-mean)^2 form is two DEPENDENT passes; a variadic lax.reduce
    lowers to a slow compare/select path on TPU — both measured worse.)"""
    n = 1.0
    for a in axes:
        n *= x.shape[a]
    x32 = x.astype(jnp.float32)
    s = jnp.sum(x32, axis=axes)
    ss = jnp.sum(jnp.square(x32), axis=axes)
    mean = s / n
    var = ss / n - jnp.square(mean)
    return mean, var, n


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def bn_train_fused(x, gamma, beta, layout, eps=1e-5):
    out, _ = _bn_fwd(x, gamma, beta, layout, eps)
    return out


def _bn_fwd(x, gamma, beta, layout, eps):
    axes = (0, 1, 2) if layout == "NHWC" else (0, 2, 3)
    shape = (1, 1, 1, -1) if layout == "NHWC" else (1, -1, 1, 1)
    mean, var, n = _moments_1pass(x, axes)
    inv = lax.rsqrt(var + eps)
    xhat_scale = inv.reshape(shape).astype(x.dtype)
    mean_b = mean.reshape(shape).astype(x.dtype)
    out = (x - mean_b) * xhat_scale * gamma.reshape(shape) + beta.reshape(shape)
    return out, (x, gamma, mean, inv)


def _bn_bwd(layout, eps, res, dy):
    x, gamma, mean, inv = res
    axes = (0, 1, 2) if layout == "NHWC" else (0, 2, 3)
    shape = (1, 1, 1, -1) if layout == "NHWC" else (1, -1, 1, 1)
    n = 1.0
    for a in axes:
        n *= x.shape[a]
    mean_b = mean.reshape(shape).astype(x.dtype)
    inv_b = inv.reshape(shape).astype(x.dtype)
    xhat = (x - mean_b) * inv_b
    # sibling reduces over dy / dy*xhat -> one multi-output fusion pass
    sdy = jnp.sum(dy.astype(jnp.float32), axis=axes)
    sdyx = jnp.sum((dy * xhat).astype(jnp.float32), axis=axes)
    dgamma = sdyx
    dbeta = sdy
    g_b = gamma.reshape(shape).astype(x.dtype)
    dx = (g_b * inv_b) * (dy
                          - (sdy / n).reshape(shape).astype(x.dtype)
                          - xhat * (sdyx / n).reshape(shape).astype(x.dtype))
    return dx, dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype)


bn_train_fused.defvjp(lambda x, g, b, layout, eps: _bn_fwd(x, g, b, layout, eps),
                      _bn_bwd)


def make_params(key, layout, pdtype):
    """ResNet-50 bottleneck v1 params as a flat dict."""
    p = {}
    init = jax.nn.initializers.he_normal()

    def wconv(name, kh, kw, cin, cout):
        k = jax.random.fold_in(key, hash(name) % (2**31))
        if layout == "NHWC":
            p[name] = init(k, (kh, kw, cin, cout), pdtype)
        else:
            p[name] = init(k, (cout, cin, kh, kw), pdtype)

    def wbn(name, c):
        p[name + "_g"] = jnp.ones((c,), pdtype)
        p[name + "_b"] = jnp.zeros((c,), pdtype)

    wconv("stem", 7, 7, 3, 64); wbn("stem_bn", 64)
    stages = [(3, 64, 256, 1), (4, 128, 512, 2), (6, 256, 1024, 2),
              (3, 512, 2048, 2)]
    cin = 64
    for s, (blocks, mid, cout, _) in enumerate(stages):
        for b in range(blocks):
            n = f"s{s}b{b}"
            wconv(n + "_c1", 1, 1, cin, mid); wbn(n + "_bn1", mid)
            wconv(n + "_c2", 3, 3, mid, mid); wbn(n + "_bn2", mid)
            wconv(n + "_c3", 1, 1, mid, cout); wbn(n + "_bn3", cout)
            if b == 0:
                wconv(n + "_sc", 1, 1, cin, cout); wbn(n + "_scbn", cout)
            cin = cout
    kf = jax.random.fold_in(key, 999)
    p["fc_w"] = (jax.random.normal(kf, (2048, 1000), pdtype) * 0.01)
    p["fc_b"] = jnp.zeros((1000,), pdtype)
    return p


def forward(p, x, layout, fused_bn=False):
    cd = jnp.bfloat16

    def c(name, x, stride=(1, 1), padding="SAME"):
        return conv(x, p[name].astype(cd), stride, padding, layout)

    def bn(name, x):
        fn = bn_train_fused if fused_bn else bn_train
        return fn(x, p[name + "_g"].astype(cd), p[name + "_b"].astype(cd),
                  layout)

    x = x.astype(cd)
    x = jax.nn.relu(bn("stem_bn", c("stem", x, (2, 2))))
    window = (1, 3, 3, 1) if layout == "NHWC" else (1, 1, 3, 3)
    strides = (1, 2, 2, 1) if layout == "NHWC" else (1, 1, 2, 2)
    x = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, "SAME")
    stages = [(3, 64, 256, 1), (4, 128, 512, 2), (6, 256, 1024, 2),
              (3, 512, 2048, 2)]
    for s, (blocks, mid, cout, first_stride) in enumerate(stages):
        for b in range(blocks):
            n = f"s{s}b{b}"
            stride = (first_stride, first_stride) if b == 0 else (1, 1)
            y = jax.nn.relu(bn(n + "_bn1", c(n + "_c1", x, stride)))
            y = jax.nn.relu(bn(n + "_bn2", c(n + "_c2", y)))
            y = bn(n + "_bn3", c(n + "_c3", y))
            sc = bn(n + "_scbn", c(n + "_sc", x, stride)) if b == 0 else x
            x = jax.nn.relu(y + sc)
    axes = (1, 2) if layout == "NHWC" else (2, 3)
    x = jnp.mean(x, axis=axes)
    return x.astype(jnp.float32) @ p["fc_w"].astype(jnp.float32) + p["fc_b"].astype(jnp.float32)


def loss_fn(p, x, y, layout, fused_bn=False):
    logits = forward(p, x, layout, fused_bn)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.sum(y * logp, axis=-1))


@partial(jax.jit, static_argnums=(4, 5), donate_argnums=(0, 1))
def train_step(p, mom, x, y, layout, fused_bn=False):
    loss, g = jax.value_and_grad(loss_fn)(p, x, y, layout, fused_bn)
    lr, mu, wd = 0.1, 0.9, 1e-4

    def upd(p_, g_, m_):
        g_ = g_.astype(jnp.float32) + wd * p_.astype(jnp.float32)
        m_new = mu * m_ + g_
        p_new = p_.astype(jnp.float32) - lr * (g_ + mu * m_new)  # nesterov
        return p_new.astype(p_.dtype), m_new

    out = jax.tree.map(upd, p, g, mom)
    p_new = {k: v[0] for k, v in out.items()}
    m_new = {k: v[1] for k, v in out.items()}
    return p_new, m_new, loss


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layout", default="NHWC", choices=["NHWC", "NCHW"])
    ap.add_argument("--params", default="bf16", choices=["f32", "bf16"])
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--hlo", action="store_true", help="dump HLO op stats")
    ap.add_argument("--fusedbn", action="store_true",
                    help="single-pass variadic-reduce BN with custom VJP")
    args = ap.parse_args()

    pdtype = jnp.bfloat16 if args.params == "bf16" else jnp.float32
    key = jax.random.PRNGKey(0)
    p = make_params(key, args.layout, pdtype)
    mom = jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32), p)
    rng = np.random.RandomState(0)
    shape = ((args.batch, 224, 224, 3) if args.layout == "NHWC"
             else (args.batch, 3, 224, 224))
    x = jnp.asarray(rng.randn(*shape).astype(np.float32))
    y = jnp.asarray(np.eye(1000, dtype=np.float32)[rng.randint(0, 1000, args.batch)])

    state = {"p": p, "m": mom, "loss": None}

    def run():
        state["p"], state["m"], state["loss"] = train_step(
            state["p"], state["m"], x, y, args.layout, args.fusedbn)

    times = _timed_steps(run, lambda: float(state["loss"]), warmup=3,
                         steps=args.steps)
    med = statistics.median(times)

    lowered = jax.jit(train_step.__wrapped__, static_argnums=(4, 5)).lower(
        state["p"], state["m"], x, y, args.layout, args.fusedbn)
    flops = None
    try:
        cost = lowered.cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        flops = float(cost.get("flops", 0)) or None
    except Exception:
        pass
    hlo_stats = {}
    if args.hlo:
        try:
            txt = lowered.compile().as_text()
            for opname in ("transpose(", "convert(", "fusion(", "convolution("):
                hlo_stats[opname.rstrip("(")] = len(re.findall(re.escape(opname), txt))
        except Exception as e:
            hlo_stats["error"] = str(e)

    out = {
        "config": f"minimal-resnet50 {args.layout} params={args.params}",
        "batch": args.batch,
        "img_per_sec": round(args.batch / med, 1),
        "step_ms_median": round(med * 1e3, 2),
        "step_ms_p10": round(float(np.percentile(times, 10)) * 1e3, 2),
        "step_ms_p90": round(float(np.percentile(times, 90)) * 1e3, 2),
        "final_loss": float(state["loss"]),
        "platform": jax.devices()[0].platform,
    }
    if flops:
        out["effective_tflops"] = round(flops / med / 1e12, 1)
        out["mfu_vs_bf16_peak"] = round(flops / med / 1e12 / TPU_BF16_PEAK_TFLOPS, 4)
    if hlo_stats:
        out["hlo_op_counts"] = hlo_stats
    print(json.dumps(out))


if __name__ == "__main__":
    main()
