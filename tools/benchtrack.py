"""benchtrack: BENCH_r*.json trajectory table + regression gates.

The driver commits one ``BENCH_r<N>.json`` per bench round in the shape
``{"n", "cmd", "rc", "tail", "parsed"}`` where ``tail`` carries the
bench's emitted JSON lines (one record per metric, ``parsed`` = the
final record). Before this module the history was write-only: nothing
read the trajectory back, rendered it, or gated a new run against it.

Two halves:

- **Trajectory** — :func:`load_rounds` parses every round file in a
  directory, :func:`trajectory` pivots them per metric, and
  :func:`render_markdown` emits the r01→rNN table BASELINE.md carries.
- **Regression gates** — :func:`compare_records` holds a current run's
  records against a baseline round: step-time, throughput, MFU,
  compile/trace counts and updater-state bytes. Noise handling follows
  the PR-11 min-over-rounds doctrine: the bench already reports
  median/p10 over >=6 timed chunks, and host-load noise only INFLATES a
  time — so the gate takes the CURRENT run's best (min of median and
  p10) against the BASELINE median plus tolerance. A noisy-but-flat run
  passes; a real regression (every chunk slower) fails. Records whose
  platform differs from the baseline's are SKIPPED with a note, never
  failed — a CPU round against a TPU baseline is not a regression
  signal. ``bench.py --compare-to <round.json>`` wires this in and
  exits non-zero on any violation.

CLI::

    python -m tools.benchtrack [--dir .] [--markdown] [--metrics a,b]
    python -m tools.benchtrack --compare BENCH_r05.json current.json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")

# default gate tolerances (fractions)
STEP_TIME_TOL = 0.10
THROUGHPUT_TOL = 0.10
MFU_TOL = 0.10
STATE_BYTES_TOL = 0.05


def _records_from_lines(text: str) -> List[Dict[str, Any]]:
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            out.append(rec)
    return out


def parse_round(path: str) -> Dict[str, Any]:
    """One round file -> {round, path, rc, records: {metric: record}}.
    Accepts the driver round shape ({n, cmd, rc, tail, parsed}), a bare
    bench record ({"metric": ...}), or a file of bench JSON lines."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    records: Dict[str, Dict[str, Any]] = {}
    n: Optional[int] = None
    rc: Optional[int] = None
    try:
        blob = json.loads(text)
    except ValueError:
        blob = None
    if isinstance(blob, dict) and "tail" in blob:
        n = blob.get("n")
        rc = blob.get("rc")
        for rec in _records_from_lines(blob.get("tail", "")):
            records[rec["metric"]] = rec     # last wins (tail truncation)
        parsed = blob.get("parsed")
        if isinstance(parsed, dict) and "metric" in parsed:
            records[parsed["metric"]] = parsed
    elif isinstance(blob, dict) and "metric" in blob:
        records[blob["metric"]] = blob
    else:
        for rec in _records_from_lines(text):
            records[rec["metric"]] = rec
    if n is None:
        m = _ROUND_RE.search(os.path.basename(path))
        if m:
            n = int(m.group(1))
    return {"round": n, "path": path, "rc": rc, "records": records}


def load_rounds(dirpath: str = ".") -> List[Dict[str, Any]]:
    """Every BENCH_r*.json under ``dirpath``, sorted by round number."""
    paths = sorted(glob.glob(os.path.join(dirpath, "BENCH_r*.json")))
    rounds = [parse_round(p) for p in paths]
    return sorted(rounds, key=lambda r: (r["round"] is None, r["round"]))


def trajectory(rounds: List[Dict[str, Any]],
               metrics: Optional[List[str]] = None
               ) -> Dict[str, List[Tuple[Optional[int], Dict[str, Any]]]]:
    """Pivot rounds per metric: {metric: [(round_n, record), ...]}."""
    out: Dict[str, List[Tuple[Optional[int], Dict[str, Any]]]] = {}
    for rnd in rounds:
        for metric, rec in sorted(rnd["records"].items()):
            if metrics is not None and metric not in metrics:
                continue
            out.setdefault(metric, []).append((rnd["round"], rec))
    return out


def _fmt(v: Any, nd: int = 2) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:,.{nd}f}".rstrip("0").rstrip(".") or "0"
    if isinstance(v, int):
        return f"{v:,}"
    return str(v)


def render_markdown(rounds: List[Dict[str, Any]],
                    metrics: Optional[List[str]] = None) -> str:
    """The BASELINE.md trajectory table: one section per metric, one row
    per round, carrying the roofline-relevant columns."""
    traj = trajectory(rounds, metrics)
    lines: List[str] = []
    for metric, rows in sorted(traj.items()):
        lines.append(f"### `{metric}`")
        lines.append("")
        lines.append("| round | value | unit | step ms (med) | MFU | "
                     "platform | batch |")
        lines.append("|---|---|---|---|---|---|---|")
        for n, rec in rows:
            lines.append(
                "| r{:02d} | {} | {} | {} | {} | {} | {} |".format(
                    n if n is not None else 0,
                    _fmt(rec.get("value")), rec.get("unit", "?"),
                    _fmt(rec.get("step_ms_median"), 3),
                    _fmt(rec.get("mfu_vs_bf16_peak"), 4),
                    rec.get("platform", "?"),
                    _fmt(rec.get("batch"))))
        lines.append("")
    return "\n".join(lines)


def _state_bytes_total(v: Any) -> Optional[float]:
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, dict):
        if "total" in v:
            return float(v["total"])
        vals = [x for x in v.values() if isinstance(x, (int, float))]
        return float(sum(vals)) if vals else None
    return None


def compare_records(baseline: Dict[str, Dict[str, Any]],
                    current: Dict[str, Dict[str, Any]],
                    step_time_tol: float = STEP_TIME_TOL,
                    throughput_tol: float = THROUGHPUT_TOL,
                    mfu_tol: float = MFU_TOL,
                    state_bytes_tol: float = STATE_BYTES_TOL
                    ) -> Dict[str, List[str]]:
    """Gate ``current`` records against ``baseline`` records (both keyed
    by metric). Returns {"violations": [...], "skipped": [...],
    "compared": [...]} — empty ``violations`` means the gate passes.

    Gates per shared metric (missing fields skip that gate, they never
    fail it):

    - **step time**: current best (min of ``step_ms_median`` and
      ``step_ms_p10`` — the noise-aware bound) must be <= baseline
      median * (1 + step_time_tol);
    - **throughput**: current ``value`` >= baseline * (1 -
      throughput_tol), only when unit AND batch match (value scales
      with batch);
    - **MFU**: current ``mfu_vs_bf16_peak`` >= baseline * (1 - mfu_tol);
    - **compile counts**: no ``traces`` counter may EXCEED its baseline
      (new compiles in a steady config are the retrace bug class);
    - **state bytes**: ``updater_state_bytes`` total <= baseline *
      (1 + state_bytes_tol) (the bf16-state win must not silently
      regress).
    """
    violations: List[str] = []
    skipped: List[str] = []
    compared: List[str] = []
    if not baseline:
        # an empty baseline round (e.g. a smoke config that emitted no
        # records, or a truncated file) is NOT a pass-by-vacuity worth
        # silence: say so, gate nothing, exit clean
        skipped.append("baseline round carries no records — nothing to "
                       "compare, skipping the regression gate")
        return {"violations": violations, "skipped": skipped,
                "compared": compared}
    for metric, base in sorted(baseline.items()):
        cur = current.get(metric)
        if cur is None:
            skipped.append(f"{metric}: not in current run")
            continue
        if base.get("platform") != cur.get("platform"):
            skipped.append(
                f"{metric}: platform changed "
                f"({base.get('platform')} -> {cur.get('platform')}) — "
                "cross-platform comparison is not a regression signal")
            continue
        compared.append(metric)
        b_med = base.get("step_ms_median")
        c_med = cur.get("step_ms_median")
        if b_med and c_med:
            c_best = min(x for x in (c_med, cur.get("step_ms_p10"))
                         if x)
            if c_best > b_med * (1.0 + step_time_tol):
                violations.append(
                    f"{metric}: step time regressed — current best "
                    f"{c_best:.3f} ms > baseline {b_med:.3f} ms "
                    f"+{step_time_tol:.0%}")
        if base.get("unit") == cur.get("unit") \
                and base.get("batch") == cur.get("batch") \
                and base.get("value") and cur.get("value") is not None:
            if cur["value"] < base["value"] * (1.0 - throughput_tol):
                violations.append(
                    f"{metric}: throughput regressed — "
                    f"{cur['value']:.2f} {cur.get('unit')} < baseline "
                    f"{base['value']:.2f} -{throughput_tol:.0%}")
        b_mfu = base.get("mfu_vs_bf16_peak")
        c_mfu = cur.get("mfu_vs_bf16_peak")
        if b_mfu and c_mfu is not None:
            if c_mfu < b_mfu * (1.0 - mfu_tol):
                violations.append(
                    f"{metric}: MFU regressed — {c_mfu:.4f} < baseline "
                    f"{b_mfu:.4f} -{mfu_tol:.0%}")
        b_tr = base.get("traces")
        c_tr = cur.get("traces")
        if isinstance(b_tr, dict) and isinstance(c_tr, dict):
            for name, c_n in sorted(c_tr.items()):
                b_n = b_tr.get(name, 0)
                if isinstance(c_n, (int, float)) and c_n > b_n:
                    violations.append(
                        f"{metric}: compile count grew — {name} "
                        f"{c_n} > baseline {b_n}")
        b_sb = _state_bytes_total(base.get("updater_state_bytes"))
        c_sb = _state_bytes_total(cur.get("updater_state_bytes"))
        if b_sb and c_sb is not None:
            if c_sb > b_sb * (1.0 + state_bytes_tol):
                violations.append(
                    f"{metric}: updater-state bytes grew — {c_sb:.0f} > "
                    f"baseline {b_sb:.0f} +{state_bytes_tol:.0%}")
    return {"violations": violations, "skipped": skipped,
            "compared": compared}


def compare_files(baseline_path: str,
                  current_path: str, **tols) -> Dict[str, List[str]]:
    base = parse_round(baseline_path)
    cur = parse_round(current_path)
    return compare_records(base["records"], cur["records"], **tols)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="BENCH_r*.json trajectory and regression gates")
    ap.add_argument("--dir", default=".",
                    help="directory holding BENCH_r*.json rounds")
    ap.add_argument("--metrics", default=None,
                    help="comma-separated metric filter")
    ap.add_argument("--markdown", action="store_true",
                    help="emit the markdown trajectory table")
    ap.add_argument("--compare", nargs=2,
                    metavar=("BASELINE", "CURRENT"),
                    help="gate CURRENT records against BASELINE; exit 1 "
                         "on any violation")
    args = ap.parse_args(argv)
    metrics = args.metrics.split(",") if args.metrics else None

    if args.compare:
        result = compare_files(*args.compare)
        print(json.dumps(result, indent=2))
        return 1 if result["violations"] else 0

    rounds = load_rounds(args.dir)
    if not rounds:
        print(f"no BENCH_r*.json rounds under {args.dir}", file=sys.stderr)
        return 2
    if args.markdown:
        print(render_markdown(rounds, metrics))
    else:
        traj = trajectory(rounds, metrics)
        for metric, rows in sorted(traj.items()):
            print(metric)
            for n, rec in rows:
                print(f"  r{n:02d}: {_fmt(rec.get('value'))} "
                      f"{rec.get('unit', '?')}  "
                      f"step {_fmt(rec.get('step_ms_median'), 3)} ms  "
                      f"mfu {_fmt(rec.get('mfu_vs_bf16_peak'), 4)}  "
                      f"[{rec.get('platform', '?')}]")
    return 0


if __name__ == "__main__":
    sys.exit(main())
