# Makes tools/ importable so `python -m tools.graftlint` works from the
# repo root (the scripts in here still run standalone).
